"""Autoregressive text generation with a static KV cache.

Parity: the reference ecosystem's generation loop (PaddleNLP
generation_utils / paddle.incubate fused generation ops — greedy, top-k,
top-p sampling over cache_kv). TPU design: the KV cache is a set of
pre-allocated fixed-shape buffers updated with
``lax.dynamic_update_slice`` so the whole decode step is ONE jitted
program (static shapes, no per-token recompilation); the prompt is
prefilled in a single batched forward, then the token loop drives the
cached step executable.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .core.autograd import no_grad
from .core.tensor import Tensor
from .observability import tracing as _tracing
from .observability.recompile import entrypoint as _entrypoint
from .utils.functional import functional_call

__all__ = ["GenerationConfig", "generate", "generate_uncached",
           "update_static_kv_cache", "make_kv_caches", "make_cached_runner",
           "select_tokens", "split_keys", "split_key_levels",
           "spec_accept_length", "spec_tree_plan", "truncated_draft",
           "make_paged_kv_pools",
           "paged_kv_cache_write", "gather_paged_kv",
           "kv_cache_write_quant", "paged_kv_cache_write_quant",
           "gather_paged_kv_dequant", "dequantize_kv_buffer",
           "kv_format_of", "kv_cache_bytes_per_token"]


def _is_per_row(position_offset) -> bool:
    """True when ``position_offset`` is a per-row [B] vector (the serving
    engine's continuous-batching decode, where every slot sits at its own
    sequence position) rather than a shared scalar."""
    return getattr(position_offset, "ndim", 0) == 1


def kv_cache_write(buf, new, position_offset):
    """Write a step's [b, s, h, d] block into a pre-allocated
    [b, max_len, h, d] cache buffer at ``position_offset`` (the
    TPU-native dynamic_update_slice form of the reference's cache_kv
    write; one of the two halves of ``update_static_kv_cache``).

    ``position_offset`` may be a shared scalar (whole-batch decode) or a
    per-row [b] vector (slot-batched serving decode) — the vector form
    vmaps the update so each row lands at its own position."""
    from .ops.dispatch import apply_op, ensure_tensor

    def upd(b, n):
        if _is_per_row(position_offset):
            return jax.vmap(
                lambda br, nr, off: jax.lax.dynamic_update_slice(
                    br, nr.astype(br.dtype), (off, 0, 0))
            )(b, n, position_offset)
        return jax.lax.dynamic_update_slice(
            b, n.astype(b.dtype), (0, position_offset, 0, 0))

    return apply_op("kv_cache_update", upd, ensure_tensor(buf),
                    ensure_tensor(new))


def _causal_cache_mask(position_offset, s: int, max_len: int) -> Tensor:
    """The additive causal mask over a static cache of ``max_len`` key
    positions for ``s`` query tokens starting at ``position_offset`` —
    shared by the contiguous and paged cache paths so both build the
    bit-identical mask (the engine's parity oracle depends on it)."""
    kpos = jnp.arange(max_len)
    if _is_per_row(position_offset):
        po = position_offset
        qpos = po[:, None] + jnp.arange(s)          # [b, s]
        m = (kpos[None, None, :] <= qpos[:, :, None]) \
            & (kpos[None, None, :] < (po[:, None, None] + s))
        return Tensor(jnp.where(m[:, None], 0.0, -1e30).astype(jnp.float32))
    qpos = position_offset + jnp.arange(s)
    m = (kpos[None, :] <= qpos[:, None]) & (kpos[None, :] < position_offset + s)
    return Tensor(jnp.where(m[None, None], 0.0, -1e30).astype(jnp.float32))


def _tree_cache_mask(position_offset, s: int, max_len: int, tree_mask):
    """Tree-speculative variant of ``_causal_cache_mask``: the ``s``
    query rows are the flattened draft-tree bundle at cache slots
    ``position_offset + i``, and ``tree_mask`` [b, s, s] (bool, True =
    visible) says which bundle slots are each node's ancestors. A node
    sees every PAST position (< offset, untouched semantics) plus its
    ancestor-or-self set inside the bundle — never a sibling branch."""
    anc = tree_mask._data if isinstance(tree_mask, Tensor) \
        else jnp.asarray(tree_mask)
    if anc.ndim != 3 or anc.shape[1] != s or anc.shape[2] != s:
        raise ValueError(
            f"tree_mask must be [batch, {s}, {s}] (one bool row per "
            f"bundle node), got shape {tuple(anc.shape)}")
    B = anc.shape[0]
    kpos = jnp.arange(max_len)
    po = position_offset._data if isinstance(position_offset, Tensor) \
        else jnp.asarray(position_offset)
    if not _is_per_row(po):
        po = jnp.broadcast_to(po, (B,))
    past = kpos[None, None, :] < po[:, None, None]          # [b, 1, max]
    rel = kpos[None, None, :] - po[:, None, None]
    in_bundle = (rel >= 0) & (rel < s)
    relc = jnp.clip(rel, 0, s - 1)
    anc_g = jnp.take_along_axis(
        anc, jnp.broadcast_to(relc, (B, s, max_len)), axis=2)
    m = past | (in_bundle & anc_g)                          # [b, s, max]
    return Tensor(jnp.where(m[:, None], 0.0, -1e30).astype(jnp.float32))


def _cache_mask(kv_cache, position_offset, s: int, max_len: int):
    """The additive cache mask for this step: the tree-ancestor mask
    when the cache dict carries one (spec-tree bundles), else the
    shared causal mask."""
    tm = kv_cache.get("tree_mask") if isinstance(kv_cache, dict) else None
    if tm is not None:
        return _tree_cache_mask(position_offset, s, max_len, tm)
    return _causal_cache_mask(position_offset, s, max_len)


def kv_format_of(arr) -> str:
    """Storage format of a KV buffer, derived from its dtype (the cache
    dict needs no extra tag: int8/fp8 storage IS the format)."""
    from .quantization import intx as _intx

    d = arr._data.dtype if isinstance(arr, Tensor) else \
        jnp.asarray(arr).dtype
    if d == jnp.int8:
        return "int8"
    fp8 = _intx.fp8_dtype()
    if fp8 is not None and d == jnp.dtype(fp8):
        return "fp8"
    return "bf16"


def kv_cache_bytes_per_token(config, kv_format: str = "bf16",
                             dtype=jnp.float32) -> int:
    """HBM bytes one cached token costs across all layers (K + V values
    plus, for quantized formats, the per-token-per-head f32 absmax
    scales) — the host-side accounting the capacity benches and the
    ``paddle_tpu_kv_bytes_per_token`` gauge report."""
    from .quantization import intx as _intx

    n_kv = config.num_key_value_heads
    head_dim = config.hidden_size // config.num_attention_heads
    if kv_format == "bf16":
        per = n_kv * head_dim * jnp.dtype(dtype).itemsize
    else:
        per = n_kv * (head_dim * _intx.format_itemsize(kv_format) + 4)
    return 2 * per * config.num_hidden_layers


def make_paged_kv_pools(config, num_blocks: int, block_size: int, dtype,
                        kv_format: str = "bf16"):
    """Device-resident paged KV pools: a list (one per decoder layer) of
    {"k", "v"} jnp arrays shaped [num_blocks, block_size,
    num_key_value_heads, head_dim]. Slots address the pool through
    per-slot int32 block tables instead of owning contiguous rows, so
    HBM is bounded by TOKENS IN FLIGHT, not slots * worst-case length.

    ``kv_format="int8"``/``"fp8"`` stores the values in the narrow dtype
    and adds per-token-per-head absmax scale pools ``ks``/``vs``
    ([num_blocks, block_size, n_kv] f32) riding the same block structure
    — writes quantize in the scatter epilogue, reads dequantize in the
    paged flash-decode prologue (or the XLA gather fallback), so KV HBM
    traffic drops ~2x and everything else (block tables, COW, prefix
    sharing, preemption) is unchanged."""
    from .quantization import intx as _intx

    n_kv = config.num_key_value_heads
    head_dim = config.hidden_size // config.num_attention_heads
    if kv_format != "bf16":
        sdt = _intx.format_dtype(kv_format)  # raises actionably for fp8
        return [{"k": jnp.zeros((num_blocks, block_size, n_kv, head_dim),
                                sdt),
                 "v": jnp.zeros((num_blocks, block_size, n_kv, head_dim),
                                sdt),
                 "ks": jnp.zeros((num_blocks, block_size, n_kv),
                                 jnp.float32),
                 "vs": jnp.zeros((num_blocks, block_size, n_kv),
                                 jnp.float32)}
                for _ in range(config.num_hidden_layers)]
    return [{"k": jnp.zeros((num_blocks, block_size, n_kv, head_dim), dtype),
             "v": jnp.zeros((num_blocks, block_size, n_kv, head_dim), dtype)}
            for _ in range(config.num_hidden_layers)]


def paged_kv_cache_write(pool, new, block_table, position_offset,
                         valid_len=None):
    """Scatter a step's [b, s, h, d] K-or-V block into the shared
    [num_blocks, block_size, h, d] pool through per-row block tables
    (the paged analogue of ``kv_cache_write``): token j of row b lands
    in physical block ``block_table[b, (pos_b + j) // block_size]`` at
    offset ``(pos_b + j) % block_size``.

    ``valid_len`` (scalar or per-row [b]) caps how many of the ``s``
    tokens are real: padded tail tokens (chunked prefill pads the last
    chunk to the fixed chunk shape) are routed into the reserved dump
    block 0 so they can never dirty a live block."""
    from .ops.dispatch import apply_op, ensure_tensor

    bt = block_table._data if isinstance(block_table, Tensor) \
        else jnp.asarray(block_table)
    po = position_offset._data if isinstance(position_offset, Tensor) \
        else position_offset
    vl = None if valid_len is None else (
        valid_len._data if isinstance(valid_len, Tensor) else valid_len)

    def upd(p, n):
        num_blocks, bs = p.shape[0], p.shape[1]
        b, s = n.shape[0], n.shape[1]
        idx = _paged_flat_indices(bt, po, vl, num_blocks, bs, b, s)
        flat = p.reshape((num_blocks * bs,) + p.shape[2:])
        flat = flat.at[idx.reshape(-1)].set(
            n.astype(p.dtype).reshape((b * s,) + n.shape[2:]))
        return flat.reshape(p.shape)

    return apply_op("paged_kv_cache_update", upd, ensure_tensor(pool),
                    ensure_tensor(new))


def _paged_flat_indices(bt, po, vl, num_blocks, bs, b, s):
    """Flat [b, s] pool indices for a paged scatter (shared by the plain
    and quantized writes): token j of row b lands at
    ``block_table[b, (pos_b + j) // bs] * bs + (pos_b + j) % bs``;
    tokens past ``valid`` route to flat slot 0 (the dump block)."""
    pos = jnp.asarray(po, jnp.int32)
    if pos.ndim == 0:
        pos = jnp.broadcast_to(pos, (b,))
    tpos = pos[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
    blk = jnp.clip(tpos // bs, 0, bt.shape[1] - 1)
    phys = jnp.take_along_axis(jnp.asarray(bt, jnp.int32), blk, axis=1)
    idx = phys * bs + tpos % bs
    if vl is not None:
        va = jnp.asarray(vl, jnp.int32)
        if va.ndim == 0:
            va = jnp.broadcast_to(va, (b,))
        idx = jnp.where(tpos < (pos + va)[:, None], idx, 0)
    return idx


def paged_kv_cache_write_quant(pool, scales, new, block_table,
                               position_offset, valid_len=None,
                               kv_format: str = "int8"):
    """The quantizing scatter epilogue: quantize this step's [b, s, h, d]
    K-or-V block PER TOKEN PER HEAD (absmax over d — a later token can
    never force already-written tokens to be requantized, which a
    block-wide scalar scale would) and scatter values + scales through
    the block table. Returns (pool', scales')."""
    from .ops.dispatch import apply_op, ensure_tensor
    from .quantization import intx as _intx

    bt = block_table._data if isinstance(block_table, Tensor) \
        else jnp.asarray(block_table)
    po = position_offset._data if isinstance(position_offset, Tensor) \
        else position_offset
    vl = None if valid_len is None else (
        valid_len._data if isinstance(valid_len, Tensor) else valid_len)

    def upd(p, sc, n):
        num_blocks, bs = p.shape[0], p.shape[1]
        b, s = n.shape[0], n.shape[1]
        idx = _paged_flat_indices(bt, po, vl, num_blocks, bs, b, s)
        amax = _intx.absmax_along(n, axis=-1)          # [b, s, h]
        q = _intx.pack_absmax(n, amax[..., None], kv_format)
        flat = p.reshape((num_blocks * bs,) + p.shape[2:])
        flat = flat.at[idx.reshape(-1)].set(
            q.reshape((b * s,) + q.shape[2:]))
        sflat = sc.reshape((num_blocks * bs,) + sc.shape[2:])
        sflat = sflat.at[idx.reshape(-1)].set(
            amax.reshape((b * s,) + amax.shape[2:]).astype(sc.dtype))
        return flat.reshape(p.shape), sflat.reshape(sc.shape)

    return apply_op("paged_kv_cache_update_quant", upd, ensure_tensor(pool),
                    ensure_tensor(scales), ensure_tensor(new))


def kv_cache_write_quant(buf, scales, new, position_offset,
                         kv_format: str = "int8"):
    """Contiguous twin of ``paged_kv_cache_write_quant``: quantize the
    step's [b, s, h, d] block per token per head and write values into
    the int8/fp8 [b, max_len, h, d] buffer + scales into the
    [b, max_len, h] f32 buffer at ``position_offset``. Returns
    (buf', scales')."""
    from .ops.dispatch import apply_op, ensure_tensor
    from .quantization import intx as _intx

    po = position_offset._data if isinstance(position_offset, Tensor) \
        else position_offset

    def upd(b, sc, n):
        amax = _intx.absmax_along(n, axis=-1)          # [bR, s, h]
        q = _intx.pack_absmax(n, amax[..., None], kv_format)
        amax = amax.astype(sc.dtype)
        if _is_per_row(po):
            nb = jax.vmap(
                lambda br, nr, off: jax.lax.dynamic_update_slice(
                    br, nr, (off, 0, 0)))(b, q, po)
            ns = jax.vmap(
                lambda br, nr, off: jax.lax.dynamic_update_slice(
                    br, nr, (off, 0)))(sc, amax, po)
            return nb, ns
        nb = jax.lax.dynamic_update_slice(b, q, (0, po, 0, 0))
        ns = jax.lax.dynamic_update_slice(sc, amax, (0, po, 0))
        return nb, ns

    return apply_op("kv_cache_update_quant", upd, ensure_tensor(buf),
                    ensure_tensor(scales), ensure_tensor(new))


def dequantize_kv_buffer(buf, scales, out_dtype=jnp.float32):
    """Dense dequantized view of a quantized contiguous cache (the XLA
    fallback read path): [b, max_len, h, d] storage + [b, max_len, h]
    absmax scales -> float [b, max_len, h, d]."""
    from .ops.dispatch import apply_op, ensure_tensor
    from .quantization import intx as _intx

    fmt = kv_format_of(buf)

    def g(p, sc):
        return _intx.unpack_absmax(p, sc[..., None], fmt, out_dtype)

    return apply_op("kv_cache_dequant", g, ensure_tensor(buf),
                    ensure_tensor(scales))


def gather_paged_kv(pool, block_table):
    """Materialize a slot-major [b, nb*block_size, h, d] view of the
    paged pool through the block tables — the XLA fallback read path
    (CPU lane / kernel-ineligible shapes). Logically identical to the
    contiguous [b, max_len, h, d] cache: positions past a row's length
    hold whatever the pool holds there, exactly like the contiguous
    cache holds zeros — both are exact no-ops under the additive
    causal mask."""
    from .ops.dispatch import apply_op, ensure_tensor

    bt = block_table._data if isinstance(block_table, Tensor) \
        else jnp.asarray(block_table)

    def g(p):
        out = jnp.take(p, jnp.asarray(bt, jnp.int32), axis=0)
        b, nb, bs = out.shape[0], out.shape[1], out.shape[2]
        return out.reshape((b, nb * bs) + p.shape[2:])

    return apply_op("paged_kv_gather", g, ensure_tensor(pool))


def gather_paged_kv_dequant(pool, scales, block_table,
                            out_dtype=jnp.float32):
    """Quantized-pool twin of ``gather_paged_kv``: materialize the
    slot-major view AND dequantize it in one fused op (the XLA gather
    fallback for quantized pools — on the kernel path the dequant
    happens in the Pallas prologue instead and this copy never
    exists)."""
    from .ops.dispatch import apply_op, ensure_tensor
    from .quantization import intx as _intx

    bt = block_table._data if isinstance(block_table, Tensor) \
        else jnp.asarray(block_table)
    fmt = kv_format_of(pool)

    def g(p, sc):
        bi = jnp.asarray(bt, jnp.int32)
        out = jnp.take(p, bi, axis=0)
        s_out = jnp.take(sc, bi, axis=0)
        b, nb, bs = out.shape[0], out.shape[1], out.shape[2]
        deq = _intx.unpack_absmax(out, s_out[..., None], fmt, out_dtype)
        return deq.reshape((b, nb * bs) + p.shape[2:])

    return apply_op("paged_kv_gather_dequant", g, ensure_tensor(pool),
                    ensure_tensor(scales))


def _update_paged_kv_cache(kv_cache: dict, k, v, position_offset,
                           build_mask: bool, gather: bool):
    """Paged half of ``update_static_kv_cache``: scatter the step's k/v
    through the block table, then either gather the slot-major view for
    the XLA attention paths (``gather=True``) or hand the raw pools back
    for the paged Pallas kernel (``gather=False``)."""
    bt = kv_cache["bt"]
    valid = kv_cache.get("valid")
    quant = "ks" in kv_cache
    new_cache = dict(kv_cache)
    if quant:
        fmt = kv_format_of(kv_cache["k"])
        ck, cks = paged_kv_cache_write_quant(
            kv_cache["k"], kv_cache["ks"], k, bt, position_offset, valid,
            fmt)
        cv, cvs = paged_kv_cache_write_quant(
            kv_cache["v"], kv_cache["vs"], v, bt, position_offset, valid,
            fmt)
        new_cache["ks"] = cks
        new_cache["vs"] = cvs
    else:
        ck = paged_kv_cache_write(kv_cache["k"], k, bt, position_offset,
                                  valid)
        cv = paged_kv_cache_write(kv_cache["v"], v, bt, position_offset,
                                  valid)
    new_cache["k"] = ck
    new_cache["v"] = cv
    bt_arr = bt._data if isinstance(bt, Tensor) else bt
    bs = int(ck._data.shape[1] if isinstance(ck, Tensor) else ck.shape[1])
    max_len = int(bt_arr.shape[1]) * bs
    mask = _cache_mask(kv_cache, position_offset, k.shape[1], max_len) \
        if build_mask else None
    if gather:
        if quant:
            cd = (k._data if isinstance(k, Tensor) else k).dtype
            return (gather_paged_kv_dequant(ck, cks, bt, cd),
                    gather_paged_kv_dequant(cv, cvs, bt, cd),
                    new_cache, mask)
        return (gather_paged_kv(ck, bt), gather_paged_kv(cv, bt),
                new_cache, mask)
    return ck, cv, new_cache, mask


def update_static_kv_cache(kv_cache: dict, k, v, position_offset,
                           build_mask: bool = True, gather: bool = True):
    """The static-cache protocol shared by the decoder models (llama/
    gpt): write this step's k/v [b, s, h, d] into the pre-allocated
    [b, max_len, h, d] buffers at ``position_offset`` and (unless the
    caller brings its own attn_mask — ``build_mask=False``) build the
    additive causal mask exposing only positions < offset + s.
    Returns (k_full, v_full, new_cache, mask_or_None).

    A per-row [b] ``position_offset`` vector produces per-row writes and
    a per-row [b, 1, s, max_len] mask (slots at different positions in
    one batch — the serving engine's decode step).

    PAGED caches (dict carries a ``"bt"`` block table, pools shaped
    [num_blocks, block_size, h, d]) scatter the write through the table
    instead; ``gather=True`` additionally materializes the slot-major
    [b, nb*block_size, h, d] view for the XLA attention fallbacks, while
    ``gather=False`` (the paged-kernel path, which reads the pool
    directly) skips that copy and returns the raw pools as (k, v)."""
    if isinstance(kv_cache, dict) and "bt" in kv_cache:
        return _update_paged_kv_cache(kv_cache, k, v, position_offset,
                                      build_mask, gather)
    if "ks" in kv_cache:  # quantized contiguous cache
        fmt = kv_format_of(kv_cache["k"])
        ck, cks = kv_cache_write_quant(kv_cache["k"], kv_cache["ks"], k,
                                       position_offset, fmt)
        cv, cvs = kv_cache_write_quant(kv_cache["v"], kv_cache["vs"], v,
                                       position_offset, fmt)
        new_cache = dict(kv_cache)
        new_cache.update({"k": ck, "v": cv, "ks": cks, "vs": cvs})
        mask = None
        if build_mask:
            max_len = int(ck._data.shape[1] if isinstance(ck, Tensor)
                          else ck.shape[1])
            mask = _cache_mask(kv_cache, position_offset, k.shape[1],
                               max_len)
        if gather:
            cd = (k._data if isinstance(k, Tensor) else k).dtype
            return (dequantize_kv_buffer(ck, cks, cd),
                    dequantize_kv_buffer(cv, cvs, cd), new_cache, mask)
        return ck, cv, new_cache, mask
    ck = kv_cache_write(kv_cache["k"], k, position_offset)
    cv = kv_cache_write(kv_cache["v"], v, position_offset)
    mask = None
    if build_mask:
        s = k.shape[1]
        max_len = int(ck._data.shape[1] if isinstance(ck, Tensor) else ck.shape[1])
        mask = _cache_mask(kv_cache, position_offset, s, max_len)
    new_cache = dict(kv_cache)
    new_cache.update({"k": ck, "v": cv})
    return ck, cv, new_cache, mask


def _mask_after_eos(gen, eos_id):
    """Replace everything after the first EOS with EOS (post-hoc, static)."""
    is_eos = gen == eos_id
    seen = jnp.cumsum(is_eos.astype(jnp.int32), axis=1) - is_eos.astype(jnp.int32)
    return jnp.where(seen > 0, eos_id, gen)


@dataclass
class GenerationConfig:
    max_new_tokens: int = 32
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    seed: int = 0


def _select_token(logits, cfg: GenerationConfig, key):
    """logits [B, V] -> next token [B]."""
    if not cfg.do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(cfg.temperature, 1e-6)
    if cfg.top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -cfg.top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    if cfg.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest logit value still inside the nucleus
        inside = cum - probs < cfg.top_p
        cutoff = jnp.min(jnp.where(inside, sorted_logits, jnp.inf), axis=-1, keepdims=True)
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def split_keys(keys):
    """Per-row PRNG advance: [B, 2] keys -> (new_keys [B, 2], subkeys
    [B, 2]), each row exactly ``jax.random.split(key)`` for that row —
    so a slot's key chain inside a batched decode step reproduces the
    ``key, sub = jax.random.split(key)`` chain ``generate`` drives for a
    single request."""
    pairs = jax.vmap(jax.random.split)(keys)  # [B, 2, 2]
    return pairs[:, 0], pairs[:, 1]


def split_key_levels(keys, n: int):
    """Walk the per-row chain ``n`` levels ahead WITHOUT committing:
    [B, 2] keys -> (levels [B, n+1, 2], subs [B, n, 2]) where
    ``levels[:, j]`` is each row's chain key after ``j`` splits
    (``levels[:, 0]`` is the input) and ``subs[:, j]`` is the subkey the
    j+1-th split yields — exactly the subkey ``split_keys`` would hand
    the sampler for the j+1-th emitted token.

    Speculative decoding needs the chain pre-walked: the verify step
    selects up to ``n`` candidate tokens with their per-token subkeys in
    one program, then commits the chain at ``levels[:, n_emit]`` — one
    split per EMITTED token, so the slot's key state stays the exact
    function of (seed, tokens emitted) the preemption-resume replay
    depends on."""
    levels, subs = [keys], []
    for _ in range(n):
        keys, sub = split_keys(keys)
        levels.append(keys)
        subs.append(sub)
    return jnp.stack(levels, axis=1), jnp.stack(subs, axis=1)


def spec_accept_length(drafts, candidates, spec_len):
    """Accepted-prefix emit count for one speculative verify round.

    ``drafts`` [B, k] are the proposed tokens, ``candidates`` [B, k+1]
    the target-model selections for every bundle position (candidate j
    is the token the target emits AFTER bundle position j, valid as
    long as every earlier draft matched), ``spec_len`` [B] the per-row
    live bundle width (0 = row idle). Returns ``n_emit`` [B] int32: the
    emitted tokens are ``candidates[b, :n_emit[b]]``.

    This is the Leviathan/Chen acceptance rule under the common-noise
    coupling this repo uses (draft and target select with the SAME
    per-position subkey): accept-with-prob-min(1, p/q) collapses to an
    exact token match, every emitted token is literally the one the
    non-speculative sampler would have drawn, and the target
    distribution is preserved because the output SEQUENCE is
    bit-identical to non-speculative decode — greedy and sampled both."""
    k = drafts.shape[1]
    match = (drafts == candidates[:, :k]).astype(jnp.int32)
    n_acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
    return jnp.minimum(n_acc + 1, jnp.asarray(spec_len, jnp.int32))


def spec_tree_plan(spec_tree):
    """Static host-side descriptor of a draft token tree with per-level
    branching factors ``spec_tree`` (e.g. ``[4, 2, 2]``): level 0 is the
    single root (the slot's current last token), level t+1 holds
    ``factors[t]`` children per level-t node, and nodes are flattened in
    BFS order — so every ancestor has a LOWER index than its
    descendants, which is what lets a per-row BFS-prefix width act as a
    truncated (shallower) tree.

    Returns a dict of numpy arrays (all static, shared by the offline
    oracle, the serving engine, and the tests):

    - ``factors`` tuple, ``depth`` D, ``nodes`` w, ``offsets`` [D+2]
      (``offsets[t]`` = first BFS index of level t, ``offsets[D+1]`` = w)
    - ``parent`` [w] int32 (``parent[0] == 0``)
    - ``depth_vec`` [w] int32 (level of each node)
    - ``anc_idx`` [w, D+1] int32: ``anc_idx[i, t]`` = node i's ancestor
      at depth t (padded with i itself past node i's depth — padded
      entries are never committed, the emit gate stops at the depth)
    - ``anc`` [w, w] bool: ancestor-or-self adjacency, the tree
      attention mask"""
    factors = tuple(int(f) for f in spec_tree)
    if not factors or any(f < 1 for f in factors):
        raise ValueError(
            f"spec_tree must be a non-empty sequence of branching "
            f"factors >= 1 per draft level, got {spec_tree!r}")
    depth = len(factors)
    offsets = [0, 1]
    wl = 1
    for f in factors:
        wl *= f
        offsets.append(offsets[-1] + wl)
    w = offsets[-1]
    parent = np.zeros(w, np.int32)
    depth_vec = np.zeros(w, np.int32)
    for t in range(depth):
        f = factors[t]
        for r in range(offsets[t + 2] - offsets[t + 1]):
            i = offsets[t + 1] + r
            parent[i] = offsets[t] + r // f
            depth_vec[i] = t + 1
    anc = np.eye(w, dtype=bool)
    for i in range(1, w):
        anc[i] |= anc[parent[i]]
    anc_idx = np.zeros((w, depth + 1), np.int32)
    for i in range(w):
        chain = [i]
        while chain[-1] != 0:
            chain.append(int(parent[chain[-1]]))
        chain.reverse()
        for t in range(depth + 1):
            anc_idx[i, t] = chain[t] if t < len(chain) else i
    return {"factors": factors, "depth": depth, "nodes": w,
            "offsets": np.asarray(offsets, np.int32), "parent": parent,
            "depth_vec": depth_vec, "anc_idx": anc_idx, "anc": anc}


# Bounded-nucleus fast path for select_tokens: a full-vocab XLA sort is
# by far the most expensive op in a decode step (CPU: ~8x a
# lax.top_k(256) on a [4, 4096] batch), so rows whose top-k filter fits
# this bound take a top_k-only path. The fallback keeps it EXACT — see
# select_tokens.
_NUCLEUS_BOUND = 256


def select_tokens(logits, keys, do_sample, temperature, top_k, top_p):
    """Per-row token selection with TRACED sampling params: [B, V]
    logits -> [B] tokens, where each row carries its own ``do_sample`` /
    ``temperature`` / ``top_k`` / ``top_p`` / PRNG key. Mixed greedy and
    sampled requests therefore share ONE compiled step program (the
    serving engine's requirement); row-wise the math is exactly
    ``_select_token`` on that row alone, so a slot's tokens match a
    standalone ``generate`` call with the same config and key chain.

    ``top_k <= 0`` and ``top_p >= 1.0`` disable their filters per row
    (same semantics as the static config path).

    Bit-exactness of the fast path: when every sampled row has
    ``0 < top_k <= _NUCLEUS_BOUND`` (and no tie straddles the bound),
    the kept set lives entirely in the top-K values, so padding those
    back to width V with -1e30 reproduces the EXACT masked-sorted array
    the full-sort path builds — every downstream softmax/cumsum/cutoff
    runs on an identical array and is bit-identical, whatever the
    backend's reduction groupings. Any row outside that envelope
    (top-p-only sampling, huge top_k, boundary ties) flips a runtime
    ``lax.cond`` to the full sort."""
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    lg = logits / jnp.maximum(temperature, 1e-6)[:, None]
    K = min(_NUCLEUS_BOUND, V)

    def _filter(sorted_desc):
        """Width-V filter math given the descending-sorted logits:
        top-k threshold at the k-th largest, then the top-p nucleus
        over the k-filtered distribution (the single-sort form: the
        'sorted filtered' array is the sorted array with the < kth
        suffix dropped to -1e30, since filtering keeps a prefix)."""
        kth_idx = jnp.clip(jnp.minimum(top_k, V) - 1, 0, V - 1).astype(jnp.int32)
        kth = jnp.take_along_axis(sorted_desc, kth_idx[:, None], axis=-1)
        kfilt = (top_k > 0)[:, None]
        out = jnp.where(kfilt & (lg < kth), -1e30, lg)
        sd = jnp.where(kfilt & (sorted_desc < kth), -1e30, sorted_desc)
        probs = jax.nn.softmax(sd, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        inside = cum - probs < top_p[:, None]
        cutoff = jnp.min(jnp.where(inside, sd, jnp.inf), axis=-1,
                         keepdims=True)
        return jnp.where((top_p < 1.0)[:, None] & (out < cutoff), -1e30, out)

    tops = jax.lax.top_k(lg, K)[0]  # [B, K], descending
    padded = jnp.concatenate(
        [tops, jnp.full((B, V - K), -1e30, lg.dtype)], axis=-1)
    # Everything downstream reads ``padded`` through an optimization
    # barrier, NEVER ``tops``: lax.top_k lowers to sort+slice, which
    # XLA:CPU pattern-matches into a fast partial-sort TopK custom call
    # — but slicing the result again gets algebraically pushed back
    # into slice-of-sort, breaking the match and silently falling back
    # to a full-vocab sort (~7x this op's cost). The barrier pins the
    # concat as a materialization point so consumers can't sink
    # through it.
    padded = jax.lax.optimization_barrier(padded)
    kth_idx = jnp.clip(jnp.minimum(top_k, K) - 1, 0, K - 1).astype(jnp.int32)
    kth = jnp.take_along_axis(padded, kth_idx[:, None], axis=-1)
    # strict: values beyond the bound are all < kth, so the kept set
    # (lg >= kth) is fully inside the top-K — no tie straddles the edge
    row_fast = (~do_sample) | ((top_k > 0) & (top_k <= K)
                               & (padded[:, K - 1] < kth[:, 0]))
    lg = jax.lax.cond(
        jnp.all(row_fast),
        lambda: _filter(padded),
        lambda: _filter(jnp.sort(lg, axis=-1)[:, ::-1]))
    # per-row categorical with that row's key: the flat random-bit draw
    # for a [V] row equals the [1, V] draw generate makes at B=1
    sampled = jax.vmap(lambda k, l: jax.random.categorical(k, l))(
        keys, lg).astype(jnp.int32)
    return jnp.where(do_sample, sampled, greedy)


def truncated_draft(model, num_layers: int):
    """Self-speculative draft: a fresh model of the same family whose
    config keeps only the first ``num_layers`` decoder layers, with the
    embeddings, those layers, the final norm, and the lm head COPIED
    from ``model`` (LayerSkip-style early-exit draft — no second
    checkpoint to ship, and the vocab matches by construction).

    Weight transfer rides ``set_state_dict``'s name matching: the
    truncated model's parameter names are a strict subset of the full
    model's (``layers.0..n-1`` / ``h.0..n-1``), so the full state dict
    restores every draft tensor and the surplus layers land in the
    ``unexpected`` list."""
    import dataclasses

    cfg = model.config
    n = int(num_layers)
    if not 1 <= n <= cfg.num_hidden_layers:
        raise ValueError(
            f"truncated_draft needs 1 <= num_layers <= "
            f"{cfg.num_hidden_layers}, got {num_layers}")
    draft = type(model)(dataclasses.replace(cfg, num_hidden_layers=n))
    missing, _ = draft.set_state_dict(model.state_dict())
    if missing:  # a family whose names don't nest — refuse loudly
        raise ValueError(
            f"truncated_draft could not map {len(missing)} draft "
            f"parameters from the source model (first: {missing[0]})")
    return draft


def make_kv_caches(config, batch_size: int, max_len: int, dtype,
                   kv_format: str = "bf16"):
    """Pre-allocated per-layer static KV buffers: a list (one per
    decoder layer) of {"k", "v"} jnp arrays shaped
    [batch_size, max_len, num_key_value_heads, head_dim].
    ``kv_format="int8"``/``"fp8"`` stores narrow values plus
    per-token-per-head absmax scales ``ks``/``vs`` ([b, max_len, n_kv]
    f32) — the contiguous twin of the quantized paged pools."""
    from .quantization import intx as _intx

    n_kv = config.num_key_value_heads
    head_dim = config.hidden_size // config.num_attention_heads
    if kv_format != "bf16":
        sdt = _intx.format_dtype(kv_format)
        return [{"k": jnp.zeros((batch_size, max_len, n_kv, head_dim), sdt),
                 "v": jnp.zeros((batch_size, max_len, n_kv, head_dim), sdt),
                 "ks": jnp.zeros((batch_size, max_len, n_kv), jnp.float32),
                 "vs": jnp.zeros((batch_size, max_len, n_kv), jnp.float32)}
                for _ in range(config.num_hidden_layers)]
    return [{"k": jnp.zeros((batch_size, max_len, n_kv, head_dim), dtype),
             "v": jnp.zeros((batch_size, max_len, n_kv, head_dim), dtype)}
            for _ in range(config.num_hidden_layers)]


def make_cached_runner(model):
    """The jit-friendly functional cached forward shared by ``generate``
    and the serving engine: ``run(pb, token_ids, caches, pos,
    attn_mask=None)`` calls the model with parameters/buffers supplied
    as the ``pb`` pytree and raw-jnp caches, returning
    (logits_jnp, new_caches_jnp). ``pos`` may be a python int, a traced
    scalar, or a per-row [B] vector (serving decode)."""

    def run(pb, token_ids, caches, pos, attn_mask=None):
        with no_grad():
            # wrap every array entry (k/v buffers, and for paged caches
            # the bt/valid companions) so the cache dict round-trips the
            # model as plain Tensors
            caches_t = [{kk: vv if isinstance(vv, Tensor) else Tensor(vv)
                         for kk, vv in c.items()} for c in caches]
            am = None
            if attn_mask is not None:
                am = attn_mask if isinstance(attn_mask, Tensor) else Tensor(attn_mask)
            logits, new_caches = functional_call(
                model, pb, Tensor(token_ids), attn_mask=am,
                kv_caches=caches_t, position_offset=pos)
        return (logits._data,
                [{kk: vv._data if isinstance(vv, Tensor) else vv
                  for kk, vv in c.items()} for c in new_caches])

    return run


def generate_uncached(model, input_ids, max_new_tokens: int = 32, do_sample: bool = False,
                      temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
                      eos_token_id: Optional[int] = None, seed: int = 0) -> Tensor:
    """Fallback decode for models without KV-cache plumbing: re-runs the
    full forward per token. Correct but O(n^2) — the cached path in
    ``generate`` is the serving path (llama and gpt both plumb it)."""
    cfg = GenerationConfig(max_new_tokens, do_sample, temperature, top_k, top_p,
                           eos_token_id, seed)
    ids = input_ids._data if isinstance(input_ids, Tensor) else jnp.asarray(input_ids)
    ids = ids.astype(jnp.int32)
    S = ids.shape[1]
    max_pos = getattr(model.config, "max_position_embeddings", None)
    if max_pos is not None and S + cfg.max_new_tokens > max_pos:
        raise ValueError(
            f"prompt ({S}) + max_new_tokens ({cfg.max_new_tokens}) exceeds "
            f"max_position_embeddings ({max_pos})")
    if cfg.max_new_tokens <= 0:
        return Tensor(ids)
    key = jax.random.PRNGKey(cfg.seed)
    with no_grad():
        for _ in range(cfg.max_new_tokens):
            logits = model(Tensor(ids))
            key, sub = jax.random.split(key)
            nxt = _select_token(logits._data[:, -1], cfg, sub)
            ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    if cfg.eos_token_id is not None:
        gen = _mask_after_eos(ids[:, S:], cfg.eos_token_id)
        ids = jnp.concatenate([ids[:, :S], gen], axis=1)
    return Tensor(ids)


def _normalize_prompts(input_ids, pad_token_id):
    """Normalize ``input_ids`` into (ids [B, S] int32, pad_lens or None).

    Accepts a [B, S] Tensor/array (classic equal-length prompts) or a
    ragged list/tuple of per-row token sequences. Ragged rows are
    LEFT-padded with ``pad_token_id`` to the longest prompt, and
    ``pad_lens`` [B] counts each row's leading pads so prefill/decode
    can mask them out of attention. A rectangular input combined with an
    explicit ``pad_token_id`` also enters ragged mode: leading
    ``pad_token_id`` tokens per row are treated as padding."""
    if isinstance(input_ids, (list, tuple)) and input_ids and \
            isinstance(input_ids[0], (list, tuple, np.ndarray)):
        rows = [np.asarray(r, dtype=np.int32).reshape(-1) for r in input_ids]
        lens = [r.shape[0] for r in rows]
        if any(l == 0 for l in lens):
            raise ValueError("empty prompt in ragged batch")
        S = max(lens)
        if len(set(lens)) > 1 and pad_token_id is None:
            raise ValueError(
                "ragged prompts (lengths %s) require pad_token_id for "
                "left-padding" % sorted(set(lens)))
        ids = np.full((len(rows), S), pad_token_id if pad_token_id is not None
                      else 0, np.int32)
        for b, r in enumerate(rows):
            ids[b, S - r.shape[0]:] = r
        if pad_token_id is None:
            return jnp.asarray(ids), None
        pad_lens = np.asarray([S - l for l in lens], np.int32)
        return jnp.asarray(ids), pad_lens
    ids = input_ids._data if isinstance(input_ids, Tensor) else jnp.asarray(input_ids)
    ids = ids.astype(jnp.int32)
    if pad_token_id is None:
        return ids, None
    arr = np.asarray(ids)
    # leading-run-of-pads per row (a pad id INSIDE the prompt is content)
    is_pad = arr == pad_token_id
    pad_lens = (np.cumprod(is_pad, axis=1)).sum(axis=1).astype(np.int32)
    pad_lens = np.minimum(pad_lens, arr.shape[1] - 1)  # never mask a whole row
    return ids, pad_lens


def _spec_row_keys(seed: int, B: int):
    """Per-row PRNG chain roots for the speculative path. B=1 uses
    ``PRNGKey(seed)`` directly — the exact chain ``generate`` walks, so
    single-row speculative output is bit-identical to plain generate for
    sampled requests too (the serving engine's per-request contract).
    B>1 rows get independent ``fold_in`` chains (plain generate draws
    all rows from one shared key per position, which rows advancing at
    different speculative rates cannot share; greedy output is
    key-independent and stays bit-identical at any B)."""
    root = jax.random.PRNGKey(seed)
    if B == 1:
        return root[None]
    return jax.vmap(lambda r: jax.random.fold_in(root, r))(
        jnp.arange(B, dtype=jnp.uint32))


def _generate_speculative(model, draft_model, ids, cfg: GenerationConfig,
                          spec_k: int):
    """Offline speculative decode (the serving lane's oracle): draft
    ``spec_k`` tokens with the small model, score every bundle position
    with the target in ONE cached forward (q_len = spec_k + 1), accept
    the longest draft prefix that matches the target's own selections.

    Under the common-noise coupling (draft and target select with the
    same per-position subkey — see ``spec_accept_length``) the emitted
    sequence is bit-identical to non-speculative ``generate``; the
    draft model only decides how many tokens each round advances.
    Rejected draft KV is rolled back BY POSITION: the next round's
    writes land on top of it before any query can attend it, so neither
    model's cache is ever copied or cleared."""
    B, S = ids.shape
    N = cfg.max_new_tokens
    k = int(spec_k)
    mcfg = model.config
    dcfg = draft_model.config
    if dcfg.vocab_size != mcfg.vocab_size:
        raise ValueError(
            f"draft/target vocab mismatch: draft vocab_size "
            f"({dcfg.vocab_size}) != target vocab_size "
            f"({mcfg.vocab_size}) — speculative decoding verifies draft "
            f"token ids against target logits, so both models must share "
            f"one tokenizer/vocab (e.g. build the draft with "
            f"generation.truncated_draft)")
    if S + N > dcfg.max_position_embeddings:
        raise ValueError(
            f"prompt ({S}) + max_new_tokens ({N}) exceeds the DRAFT "
            f"model's max_position_embeddings "
            f"({dcfg.max_position_embeddings}); the draft decodes the "
            f"same positions the target does")
    dtype = next(iter(model.parameters()))._data.dtype
    ddtype = next(iter(draft_model.parameters()))._data.dtype
    # verify bundles write [pos, pos+k]; the +k tail keeps every per-row
    # dynamic_update_slice window in bounds (a clamped start would SHIFT
    # the write over live entries)
    cache_len = S + N + k
    run = make_cached_runner(model)
    drun = make_cached_runner(draft_model)
    pb = {**{kk: v._data for kk, v in model.named_parameters_dict().items()},
          **{kk: v._data for kk, v in model.named_buffers_dict().items()}}
    dpb = {**{kk: v._data
              for kk, v in draft_model.named_parameters_dict().items()},
           **{kk: v._data
              for kk, v in draft_model.named_buffers_dict().items()}}
    # row-wise traced params: select_tokens row-wise == the config-static
    # _select_token, so these selections ARE plain generate's
    ds = jnp.full((B,), cfg.do_sample)
    temp = jnp.full((B,), cfg.temperature, jnp.float32)
    tkv = jnp.full((B,), cfg.top_k, jnp.int32)
    tpv = jnp.full((B,), cfg.top_p, jnp.float32)

    from .pallas_kernels.decode_attention import flash_decode_enabled
    from .pallas_kernels.quant_matmul import quant_matmul_enabled

    darch = (type(draft_model).__name__, dcfg.num_hidden_layers,
             dcfg.hidden_size, dcfg.num_attention_heads,
             dcfg.num_key_value_heads, dcfg.intermediate_size)
    gen_key = ("spec", B, S, N, k, cfg.do_sample, cfg.temperature,
               cfg.top_k, cfg.top_p, darch, flash_decode_enabled(),
               quant_matmul_enabled())
    cache_store = model.__dict__.setdefault("_generate_jit_cache", {})
    if gen_key not in cache_store:

        @jax.jit
        def sprefill(pb, dpb, ids, keys):
            caches = make_kv_caches(mcfg, B, cache_len, dtype)
            dcaches = make_kv_caches(dcfg, B, cache_len, ddtype)
            logits, caches = run(pb, ids, caches, 0)
            _, dcaches = drun(dpb, ids, dcaches, 0)
            levels, subs = split_key_levels(keys, 1)
            token = select_tokens(logits[:, -1], subs[:, 0], ds, temp,
                                  tkv, tpv)
            return token, levels[:, 1], caches, dcaches

        @functools.partial(jax.jit, donate_argnums=(1,))
        def sdraft(dpb, dcaches, tokens, pos, keys):
            # the draft proposes with the SAME subkeys the verify step
            # will select with (common-noise coupling): the proposal IS
            # the draft's guess of the target's next selection
            _, subs = split_key_levels(keys, k)
            tok = tokens
            drafts = []
            for j in range(k):
                logits, dcaches = drun(dpb, tok[:, None], dcaches, pos + j)
                tok = select_tokens(logits[:, 0], subs[:, j], ds, temp,
                                    tkv, tpv)
                drafts.append(tok)
            # write-only forward for the last draft token's KV: a full
            # accept advances past pos+k, and without this the next
            # round's draft attends a hole there (accept rate drops;
            # outputs unaffected — verify is target-authoritative)
            _, dcaches = drun(dpb, tok[:, None], dcaches, pos + k)
            return jnp.stack(drafts, axis=1), dcaches

        @functools.partial(jax.jit, donate_argnums=(1,))
        def sverify(pb, caches, tokens, drafts, pos, keys, spec_len):
            bundle = jnp.concatenate([tokens[:, None], drafts], axis=1)
            logits, caches = run(pb, bundle, caches, pos)  # [B, k+1, V]
            levels, subs = split_key_levels(keys, k + 1)
            V = logits.shape[-1]

            def _rep(x):
                return jnp.broadcast_to(
                    x[:, None], (B, k + 1)).reshape(B * (k + 1))

            cand = select_tokens(
                logits.reshape(B * (k + 1), V),
                subs.reshape(B * (k + 1), 2),
                _rep(ds), _rep(temp), _rep(tkv), _rep(tpv)).reshape(B, k + 1)
            n_emit = spec_accept_length(drafts, cand, spec_len)
            new_keys = jnp.take_along_axis(
                levels, n_emit[:, None, None], axis=1)[:, 0]
            last = jnp.take_along_axis(
                cand, jnp.maximum(n_emit - 1, 0)[:, None], axis=1)[:, 0]
            new_tok = jnp.where(n_emit > 0, last, tokens)
            return cand, n_emit, new_keys, new_tok, caches

        cache_store[gen_key] = (sprefill, sdraft, sverify)
    sprefill, sdraft, sverify = cache_store[gen_key]

    with _entrypoint("generation.generate"), \
            _tracing.span("generation.spec_decode", cat="generation",
                          args={"B": B, "S": S, "N": N, "k": k}):
        keys = _spec_row_keys(cfg.seed, B)
        token, keys, caches, dcaches = sprefill(pb, dpb, jnp.asarray(ids),
                                                keys)
        tok_np = np.asarray(token)
        out = [[int(tok_np[b])] for b in range(B)]
        emitted = np.ones(B, np.int64)
        pos = np.full(B, S, np.int64)
        while int(emitted.min()) < N:
            spec_len = np.minimum(k + 1, N - emitted).astype(np.int32)
            drafts, dcaches = sdraft(dpb, dcaches, token,
                                     jnp.asarray(pos, jnp.int32), keys)
            cand, n_emit, keys, token, caches = sverify(
                pb, caches, token, drafts, jnp.asarray(pos, jnp.int32),
                keys, jnp.asarray(spec_len))
            n_np = np.asarray(n_emit)
            cand_np = np.asarray(cand)
            for b in range(B):
                out[b].extend(int(t) for t in cand_np[b, :n_np[b]])
            pos += n_np
            emitted += n_np
    gen = jnp.asarray(np.stack([np.asarray(r[:N], np.int32) for r in out]))
    if cfg.eos_token_id is not None:
        gen = _mask_after_eos(gen, cfg.eos_token_id)
    return Tensor(jnp.concatenate([ids, gen], axis=1))


def _generate_speculative_tree(model, draft_model, ids,
                               cfg: GenerationConfig, spec_tree):
    """Offline TREE-speculative decode (the serving tree lane's oracle):
    the draft proposes a branching token tree (``spec_tree`` branching
    factors per level), the target scores the whole flattened tree of w
    nodes in ONE cached forward under the tree-ancestor mask, and
    acceptance walks the deepest root-to-leaf path whose every node
    matches the target's own selection for its parent.

    PRNG coupling per branch: all nodes at depth t share the chain
    subkey ``subs[:, t]`` at VERIFY (any node whose ancestor chain fully
    matched carries the true chain prefix, so its selection IS the
    non-speculative sampler's draw); at DRAFT time branch 0 of each node
    proposes with that same subkey (the exact chain guess) and branches
    r>0 diversify via ``fold_in`` on the child's global tree index.
    Emitted sequences stay bit-identical to non-speculative ``generate``
    — greedy and sampled — the tree only changes how many tokens each
    round advances.

    Accepted-path KV is committed BY POSITION in both models' caches
    (gather the path nodes' slots, scatter them onto the contiguous
    positions; non-committed entries route back onto their own slot, a
    same-value no-op), and the next round's writes land on top of every
    rejected slot before any query can attend it."""
    plan = spec_tree_plan(spec_tree)
    D, w = plan["depth"], plan["nodes"]
    off = [int(o) for o in plan["offsets"]]
    factors = plan["factors"]
    parent = jnp.asarray(plan["parent"])
    depth_vec = jnp.asarray(plan["depth_vec"])
    anc_idx = jnp.asarray(plan["anc_idx"])
    anc = jnp.asarray(plan["anc"])
    B, S = ids.shape
    N = cfg.max_new_tokens
    mcfg = model.config
    dcfg = draft_model.config
    if dcfg.vocab_size != mcfg.vocab_size:
        raise ValueError(
            f"draft/target vocab mismatch: draft vocab_size "
            f"({dcfg.vocab_size}) != target vocab_size "
            f"({mcfg.vocab_size}) — speculative decoding verifies draft "
            f"token ids against target logits, so both models must share "
            f"one tokenizer/vocab (e.g. build the draft with "
            f"generation.truncated_draft)")
    if S + N + D > min(dcfg.max_position_embeddings,
                       mcfg.max_position_embeddings):
        raise ValueError(
            f"prompt ({S}) + max_new_tokens ({N}) + tree depth ({D}) "
            f"exceeds max_position_embeddings "
            f"({min(dcfg.max_position_embeddings, mcfg.max_position_embeddings)}) "
            f"— tree nodes take RoPE/positional indices up to pos + depth")
    dtype = next(iter(model.parameters()))._data.dtype
    ddtype = next(iter(draft_model.parameters()))._data.dtype
    # verify bundles write [pos, pos+w-1]; the +w tail keeps every
    # per-row write window in bounds (the draft always drafts the FULL
    # tree — the accept gate, not the draft, enforces per-row budgets)
    cache_len = S + N + w
    run = make_cached_runner(model)
    drun = make_cached_runner(draft_model)
    pb = {**{kk: v._data for kk, v in model.named_parameters_dict().items()},
          **{kk: v._data for kk, v in model.named_buffers_dict().items()}}
    dpb = {**{kk: v._data
              for kk, v in draft_model.named_parameters_dict().items()},
           **{kk: v._data
              for kk, v in draft_model.named_buffers_dict().items()}}
    ds = jnp.full((B,), cfg.do_sample)
    temp = jnp.full((B,), cfg.temperature, jnp.float32)
    tkv = jnp.full((B,), cfg.top_k, jnp.int32)
    tpv = jnp.full((B,), cfg.top_p, jnp.float32)

    from .pallas_kernels.decode_attention import flash_decode_enabled
    from .pallas_kernels.quant_matmul import quant_matmul_enabled

    def _rep(x, m):
        return jnp.broadcast_to(x[:, None], (B, m)).reshape(B * m)

    def _with_tree(caches, n):
        tm = jnp.broadcast_to(anc[:n, :n][None], (B, n, n))
        return [dict(c, tree_mask=tm, tree_depth=depth_vec[:n])
                for c in caches]

    def _strip(caches):
        return [{kk: c[kk] for kk in ("k", "v")} for c in caches]

    def _kv_path_move(caches, src, dst):
        # gather the [B, D+1] source slots, scatter onto the dest slots
        # (functional: every gather reads the pre-move buffer; routed
        # no-op writes collide only with identical values)
        def mv(buf):
            return jax.vmap(lambda bu, s_, d_: bu.at[d_].set(bu[s_]))(
                buf, src, dst)
        return [{kk: mv(vv) for kk, vv in c.items()} for c in caches]

    darch = (type(draft_model).__name__, dcfg.num_hidden_layers,
             dcfg.hidden_size, dcfg.num_attention_heads,
             dcfg.num_key_value_heads, dcfg.intermediate_size)
    gen_key = ("spec_tree", B, S, N, factors, cfg.do_sample,
               cfg.temperature, cfg.top_k, cfg.top_p, darch,
               flash_decode_enabled(), quant_matmul_enabled())
    cache_store = model.__dict__.setdefault("_generate_jit_cache", {})
    if gen_key not in cache_store:

        @jax.jit
        def tprefill(pb, dpb, ids, keys):
            caches = make_kv_caches(mcfg, B, cache_len, dtype)
            dcaches = make_kv_caches(dcfg, B, cache_len, ddtype)
            logits, caches = run(pb, ids, caches, 0)
            _, dcaches = drun(dpb, ids, dcaches, 0)
            levels, subs = split_key_levels(keys, 1)
            token = select_tokens(logits[:, -1], subs[:, 0], ds, temp,
                                  tkv, tpv)
            return token, levels[:, 1], caches, dcaches

        @functools.partial(jax.jit, donate_argnums=(1,))
        def tdraft(dpb, dcaches, tokens, pos, keys):
            # level-t forward re-feeds the WHOLE tree-so-far (square
            # ancestor mask — past-KV masking stays untouched, so a
            # rectangular "new nodes only" query is not expressible);
            # earlier nodes' KV is rewritten bit-identically
            _, subs = split_key_levels(keys, D + 1)
            tok_tree = jnp.zeros((B, w), jnp.int32).at[:, 0].set(tokens)
            for t in range(D):
                n = off[t + 1]
                logits, dc = drun(dpb, tok_tree[:, :n],
                                  _with_tree(dcaches, n), pos)
                dcaches = _strip(dc)
                lvl = logits[:, off[t]:n]             # [B, w_t, V]
                f = factors[t]
                w_next = off[t + 2] - off[t + 1]
                # greedy: branch 0 = argmax EXPLICITLY (bit-parity with
                # the verify selection under any top_k tie-break),
                # branches r>0 = the r-th ranked token
                tk = jax.lax.top_k(lvl, f)[1].astype(jnp.int32)
                tk = tk.at[:, :, 0].set(
                    jnp.argmax(lvl, axis=-1).astype(jnp.int32))
                children = tk.reshape(B, w_next)
                if cfg.do_sample:
                    V = lvl.shape[-1]
                    base = subs[:, t]                 # the chain subkey
                    gidx = off[t + 1] + jnp.arange(w_next,
                                                   dtype=jnp.uint32)
                    folded = jax.vmap(lambda kk: jax.vmap(
                        lambda g: jax.random.fold_in(kk, g))(gidx))(base)
                    use_base = (jnp.arange(w_next) % f) == 0
                    keys_lvl = jnp.where(
                        use_base[None, :, None],
                        jnp.broadcast_to(base[:, None], (B, w_next, 2)),
                        folded)
                    sampled = select_tokens(
                        jnp.repeat(lvl, f, axis=1).reshape(B * w_next, V),
                        keys_lvl.reshape(B * w_next, 2),
                        _rep(ds, w_next), _rep(temp, w_next),
                        _rep(tkv, w_next),
                        _rep(tpv, w_next)).reshape(B, w_next)
                    children = jnp.where(ds[:, None], sampled, children)
                tok_tree = tok_tree.at[:, off[t + 1]:off[t + 2]].set(
                    children)
            # write-only forward at full width: leaf KV, so a deep
            # accept never leaves the draft attending a hole next round
            _, dc = drun(dpb, tok_tree, _with_tree(dcaches, w), pos)
            return tok_tree[:, 1:], _strip(dc)

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def tverify(pb, caches, dcaches, tokens, drafts, pos, keys,
                    spec_len):
            bundle = jnp.concatenate([tokens[:, None], drafts], axis=1)
            logits, cl = run(pb, bundle, _with_tree(caches, w), pos)
            caches = _strip(cl)
            levels, subs = split_key_levels(keys, D + 1)
            node_keys = jnp.take(subs, depth_vec, axis=1)  # [B, w, 2]
            V = logits.shape[-1]
            cand = select_tokens(
                logits.reshape(B * w, V), node_keys.reshape(B * w, 2),
                _rep(ds, w), _rep(temp, w), _rep(tkv, w),
                _rep(tpv, w)).reshape(B, w)
            # deepest fully-matching root-to-leaf path: a node survives
            # iff its own token matches the target's selection for its
            # parent AND every ancestor survives (D parent-AND sweeps)
            match = jnp.concatenate(
                [jnp.ones((B, 1), bool),
                 bundle[:, 1:] == jnp.take(cand, parent[1:], axis=1)],
                axis=1)
            acc = match & (jnp.arange(w)[None, :]
                           < jnp.asarray(spec_len, jnp.int32)[:, None])
            for _ in range(D):
                acc = acc & jnp.take(acc, parent, axis=1)
            score = jnp.where(acc, depth_vec[None, :] + 1, 0)
            best = jnp.argmax(score, axis=1)
            n_emit = jnp.take_along_axis(score, best[:, None],
                                         axis=1)[:, 0]
            path = jnp.take(anc_idx, best, axis=0)         # [B, D+1]
            emitted = jnp.take_along_axis(cand, path, axis=1)
            new_keys = jnp.take_along_axis(
                levels, n_emit[:, None, None], axis=1)[:, 0]
            last = jnp.take_along_axis(cand, best[:, None], axis=1)[:, 0]
            new_tok = jnp.where(n_emit > 0, last, tokens)
            # commit the accepted path by position in BOTH caches:
            # slot pos+t <- slot pos+path[t] for 1 <= t < n_emit, every
            # other entry routes back onto its own source slot (no-op)
            tt = jnp.arange(D + 1)[None, :]
            src = pos[:, None] + path
            dst = pos[:, None] + tt
            commit = (tt < n_emit[:, None]) & (tt >= 1)
            dst = jnp.where(commit, dst, src)
            caches = _kv_path_move(caches, src, dst)
            dcaches = _kv_path_move(dcaches, src, dst)
            return (emitted, n_emit, new_keys, new_tok, caches, dcaches)

        cache_store[gen_key] = (tprefill, tdraft, tverify)
    tprefill, tdraft, tverify = cache_store[gen_key]

    with _entrypoint("generation.generate"), \
            _tracing.span("generation.spec_tree_decode", cat="generation",
                          args={"B": B, "S": S, "N": N,
                                "factors": list(factors), "nodes": w}):
        keys = _spec_row_keys(cfg.seed, B)
        token, keys, caches, dcaches = tprefill(pb, dpb, jnp.asarray(ids),
                                                keys)
        tok_np = np.asarray(token)
        out = [[int(tok_np[b])] for b in range(B)]
        emitted_n = np.ones(B, np.int64)
        pos = np.full(B, S, np.int64)
        while int(emitted_n.min()) < N:
            # per-row BFS-prefix width: clamp the tree DEPTH to the
            # remaining budget (0 remaining -> width 0 -> row idles)
            rem = N - emitted_n
            spec_len = np.asarray(
                [off[min(D, int(r) - 1) + 1] if r > 0 else 0
                 for r in rem], np.int32)
            drafts, dcaches = tdraft(dpb, dcaches, token,
                                     jnp.asarray(pos, jnp.int32), keys)
            em, n_emit, keys, token, caches, dcaches = tverify(
                pb, caches, dcaches, token, drafts,
                jnp.asarray(pos, jnp.int32), keys, jnp.asarray(spec_len))
            n_np = np.asarray(n_emit)
            em_np = np.asarray(em)
            for b in range(B):
                out[b].extend(int(t) for t in em_np[b, :n_np[b]])
            pos += n_np
            emitted_n += n_np
    gen = jnp.asarray(np.stack([np.asarray(r[:N], np.int32) for r in out]))
    if cfg.eos_token_id is not None:
        gen = _mask_after_eos(gen, cfg.eos_token_id)
    return Tensor(jnp.concatenate([ids, gen], axis=1))


def generate(model, input_ids, max_new_tokens: int = 32, do_sample: bool = False,
             temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
             eos_token_id: Optional[int] = None, seed: int = 0,
             loop_mode: str = "scan", pad_token_id: Optional[int] = None,
             stream: bool = False, draft_model=None, spec_k: int = 4,
             spec_tree=None, kv_format: str = "bf16", tp: int = 1):
    """Generate continuations for ``input_ids`` [B, S]; returns [B, S+N].

    Greedy by default; sampling with temperature/top-k/top-p when
    ``do_sample``. Stops early only via post-hoc masking (static shapes).

    ``loop_mode="scan"`` (default) compiles the WHOLE decode loop into one
    program (``lax.scan`` over the token index) — one dispatch for N
    tokens, which is what makes decode fast over a remote PJRT transport;
    ``"python"`` drives one jitted step per token (useful for streaming
    consumers that want tokens as they land). In python mode with an
    ``eos_token_id`` the token loop exits as soon as every row has
    emitted EOS (the result is padded back to [B, S+N] with EOS, so the
    output contract is unchanged).

    Ragged prompts: pass a list of per-row token sequences (or a
    pre-padded [B, S] batch) together with ``pad_token_id`` — rows are
    LEFT-padded and an attention mask hides the pads through prefill AND
    every decode step. Pad positions keep their absolute cache/RoPE
    indices: RoPE scores depend only on relative distance, so a
    left-padded row decodes exactly like its unpadded twin (for learned
    position embeddings the shift is absolute, like other left-padding
    implementations).

    ``stream=True`` (forces python mode) returns a generator that yields
    one np.int32 [B] token vector per generated position as it lands
    (EOS-masked rows keep yielding EOS) and stops early once every row
    is done.

    ``draft_model=`` enables SPECULATIVE decoding (offline oracle for
    the serving engine's spec lane): the draft proposes ``spec_k``
    tokens per round and the target scores the whole bundle in one
    cached forward. Outputs are bit-identical to the non-speculative
    path — greedy at any batch size, sampled at B=1 (B>1 sampled rows
    use independent per-row key chains; see ``_spec_row_keys``) — the
    draft only changes how fast rows advance. Unsupported together with
    ``stream`` and with ragged/left-padded prompts (``pad_token_id``).

    ``spec_tree=[4, 2, 2]`` (requires ``draft_model``, replaces the
    single ``spec_k`` chain) drafts a branching token TREE instead: the
    draft samples ``factors[t]`` children per level-t node, the target
    scores the whole flattened tree in one forward under the
    tree-ancestor mask, and the deepest fully-matching root-to-leaf
    path is emitted. Same bit-parity contract as the chain lane; see
    ``spec_tree_plan`` for the flattening.

    ``kv_format="int8"``/``"fp8"`` stores the KV cache quantized
    (per-token-per-head absmax scales; fp8 = e4m3 where the jnp dtype
    exists, int8 the portable floor): cache writes quantize, the
    flash-decode kernel dequantizes in its prologue (the XLA fallback
    dequantizes at the gather), halving decode KV bytes. Greedy outputs
    at the tiny-model test points match bf16 token-for-token (pinned in
    tests/test_quantization_serving.py); logits move by the absmax
    rounding step. Not supported with ``draft_model`` here — the
    serving engine's spec lane runs on quantized pools instead.

    ``tp=N`` runs the whole generate tensor-parallel over the first N
    devices (the offline oracle for the serving engine's tp lane): the
    params are rule-sharded Megatron-style via
    ``distributed.partition.partition_rules_for(model)``, the KV caches
    shard on the kv-heads axis, and the executables compile with
    explicit shardings. Token outputs are bit-identical to tp=1 at the
    test points (logits agree to psum reduction order). The params are
    re-placed on the mesh each call — an oracle path, not a serving
    path. Not supported with ``draft_model`` (the engine's spec lane is
    the sharded one)."""
    cfg = GenerationConfig(max_new_tokens, do_sample, temperature, top_k, top_p,
                           eos_token_id, seed)
    from .quantization.intx import KV_FORMATS

    if kv_format not in KV_FORMATS:
        raise ValueError(
            f"kv_format must be one of {KV_FORMATS}, got {kv_format!r}")
    if kv_format != "bf16":
        from .quantization.intx import format_dtype

        format_dtype(kv_format)  # actionable error when fp8 is absent
        if draft_model is not None:
            raise ValueError(
                "kv_format is not supported with draft_model in offline "
                "generate — run speculative decoding on the serving "
                "engine (ServingConfig.kv_format), whose draft/verify "
                "lane operates on quantized pools")
    tp = int(tp)
    if tp > 1 and draft_model is not None:
        raise ValueError(
            "tp > 1 is not supported with draft_model in offline "
            "generate — run speculative decoding on the serving engine "
            "(ServingConfig(tp=N, ...) with draft_model), whose "
            "draft/verify executables compile over the TP mesh")
    ids, pad_lens = _normalize_prompts(input_ids, pad_token_id)
    ragged = pad_lens is not None
    B, S = ids.shape
    max_len = S + cfg.max_new_tokens
    config = model.config
    if max_len > config.max_position_embeddings:
        raise ValueError(
            f"prompt ({S}) + max_new_tokens ({cfg.max_new_tokens}) exceeds "
            f"max_position_embeddings ({config.max_position_embeddings}); the "
            "position table (RoPE / learned embeddings) has no entries past "
            "that position")
    dtype = next(iter(model.parameters()))._data.dtype

    params = {k: v._data for k, v in model.named_parameters_dict().items()}
    buffers = {k: v._data for k, v in model.named_buffers_dict().items()}

    def make_caches():
        return make_kv_caches(config, B, max_len, dtype, kv_format)

    base_run = make_cached_runner(model)

    def run(pb, token_ids, caches, pos, pads=None):
        if pads is None:
            return base_run(pb, token_ids, caches, pos)
        # ragged: causal mask that ALSO hides each row's left pads, for
        # prefill and for every decode step (pads live at cache positions
        # 0..pad_len-1 forever, so the default causal mask would attend
        # them)
        s = token_ids.shape[1]
        kpos = jnp.arange(max_len)
        qpos = pos + jnp.arange(s)
        m = (kpos[None, :] <= qpos[:, None]) & (kpos[None, :] < pos + s)
        m = m[None] & (kpos[None, None, :] >= pads[:, None, None])
        mask = jnp.where(m[:, None], 0.0, -1e30).astype(jnp.float32)
        return base_run(pb, token_ids, caches, pos, attn_mask=mask)

    if stream:
        loop_mode = "python"
    if loop_mode not in ("scan", "python"):
        raise ValueError(f"loop_mode must be 'scan' or 'python', got {loop_mode!r}")
    if cfg.max_new_tokens <= 0:
        if stream:
            return iter(())
        return Tensor(ids)
    if spec_tree is not None and draft_model is None:
        raise ValueError(
            "spec_tree requires draft_model: the tree nodes are drafted "
            "by the small model — pass draft_model= (e.g. "
            "generation.truncated_draft) or drop spec_tree")
    if draft_model is not None and (spec_tree is not None or spec_k >= 1):
        if stream:
            raise ValueError(
                "stream=True is not supported with draft_model: the "
                "speculative loop emits a variable number of tokens per "
                "round — drop draft_model to stream, or poll the serving "
                "engine's Request.stream()")
        if ragged:
            raise ValueError(
                "draft_model is not supported with ragged/left-padded "
                "prompts (pad_token_id): the speculative verify derives "
                "its masking from positions only — pass equal-length "
                "prompts or drop draft_model")
        if spec_tree is not None:
            return _generate_speculative_tree(model, draft_model, ids,
                                              cfg, spec_tree)
        return _generate_speculative(model, draft_model, ids, cfg, spec_k)

    # jitted executables are cached on the model so repeat generate() calls
    # with the same shapes/config reuse the compiled programs; the KV cache
    # pytree is donated so decode updates buffers in place
    # eos only shapes the scan-mode whole-generate program; python-mode
    # executables are eos-independent (masking happens outside jit) and
    # must not recompile per eos id
    # the flash-decode env gate is a python-side dispatch baked into the
    # trace: flipping it must not reuse executables traced the other way
    from .pallas_kernels.decode_attention import flash_decode_enabled
    from .pallas_kernels.quant_matmul import quant_matmul_enabled

    gen_key = (B, S, cfg.max_new_tokens, cfg.do_sample, cfg.temperature,
               cfg.top_k, cfg.top_p,
               cfg.eos_token_id if loop_mode == "scan" else None, loop_mode,
               ragged, flash_decode_enabled(), kv_format,
               quant_matmul_enabled(), tp)

    # tensor-parallel oracle path: rule-shard the params over a tp-mesh
    # and compile the executables with explicit shardings (the same
    # fixpoint discipline as the serving engine's tp executables — see
    # distributed/partition.py)
    tp_mesh_obj = None
    if tp > 1:
        from .distributed import partition as _partition

        _partition.validate_tp(config, tp)
        tp_mesh_obj = _partition.tp_mesh(tp)
        _tp_rules = _partition.partition_rules_for(model)
        _rep = _partition.replicated(tp_mesh_obj)
        from jax.sharding import NamedSharding as _NS

        _pb_sh = {
            name: _NS(tp_mesh_obj, spec)
            for name, spec in _partition.match_partition_rules(
                _tp_rules, {**params, **buffers}).items()}
        _ckeys = {"k": 4, "v": 4}
        if kv_format != "bf16":
            _ckeys.update({"ks": 3, "vs": 3})
        _cache_sh = [
            {kk: _NS(tp_mesh_obj, _partition.kv_cache_spec(nd))
             for kk, nd in _ckeys.items()}
            for _ in range(config.num_hidden_layers)]

    cache_store = model.__dict__.setdefault("_generate_jit_cache", {})
    if gen_key not in cache_store:

        def prefill(pb, ids, caches, pads):
            logits, caches = run(pb, ids, caches, 0, pads)
            return logits[:, -1], caches

        def step(pb, token, caches, pos, key, pads):
            logits, caches = run(pb, token[:, None], caches, pos, pads)
            nxt = _select_token(logits[:, 0], cfg, key)
            return nxt, caches

        def generate_program(pb, ids, key, pads):
            """The WHOLE generate as ONE program: cache init + prefill +
            first-token select + (N-1)-step ``lax.scan`` decode + EOS
            masking + prompt concat. A single dispatch and a single
            result transfer — per-token (or even per-phase) python
            dispatch dominates end-to-end latency on a remote PJRT
            transport (measured 3.2s -> 0.5s for 16x256 tokens on the
            134M model over the axon tunnel)."""
            caches = make_caches()
            logits, caches = run(pb, ids, caches, 0, pads)
            key, sub = jax.random.split(key)
            token = _select_token(logits[:, -1], cfg, sub)

            def body(carry, i):
                token, caches, key = carry
                key, sub = jax.random.split(key)
                logits, caches = run(pb, token[:, None], caches, S + i, pads)
                nxt = _select_token(logits[:, 0], cfg, sub)
                return (nxt, caches, key), nxt

            (_, caches, _), toks = jax.lax.scan(
                body, (token, caches, key),
                jnp.arange(cfg.max_new_tokens - 1, dtype=jnp.int32))
            gen = jnp.concatenate([token[:, None], jnp.swapaxes(toks, 0, 1)],
                                  axis=1)  # [B, N]
            if cfg.eos_token_id is not None:
                gen = _mask_after_eos(gen, cfg.eos_token_id)
            return jnp.concatenate([ids, gen], axis=1)

        if tp > 1:
            # explicit in/out shardings on every executable keep the
            # KV-cache layouts a fixpoint across calls (one compile per
            # gen_key, same as tp=1)
            prefill = _partition.tp_jit(
                prefill, tp=tp, mesh=tp_mesh_obj,
                in_shardings=(_pb_sh, _rep, _cache_sh, _rep),
                out_shardings=(_rep, _cache_sh))
            step = _partition.tp_jit(
                step, tp=tp, mesh=tp_mesh_obj,
                in_shardings=(_pb_sh, _rep, _cache_sh, _rep, _rep, _rep),
                out_shardings=(_rep, _cache_sh),
                donate_argnums=(2,))
            generate_program = _partition.tp_jit(
                generate_program, tp=tp, mesh=tp_mesh_obj,
                in_shardings=(_pb_sh, _rep, _rep, _rep),
                out_shardings=_rep)
        else:
            prefill = jax.jit(prefill)
            step = jax.jit(step, donate_argnums=(2,))
            generate_program = jax.jit(generate_program)

        cache_store[gen_key] = (prefill, step, generate_program)
    prefill, step, generate_program = cache_store[gen_key]

    pb = {**params, **buffers}
    if tp > 1:
        pb = {name: jax.device_put(v, _pb_sh[name])
              for name, v in pb.items()}
        from .observability import perf as _perf_mesh
        _perf_mesh.note_entry_mesh("generation.generate", {"tp": tp})
    key = jax.random.PRNGKey(cfg.seed)
    pads = jnp.asarray(pad_lens) if ragged else None

    def python_token_iter():
        """One jitted step per token; yields the np.int32 [B] token
        vector per position, EOS-masked, exiting early once every row
        has emitted EOS."""
        with _entrypoint("generation.generate"):
            with _tracing.span("generation.prefill", cat="generation",
                               args={"B": B, "S": S}):
                caches = make_caches()
                last_logits, caches = prefill(pb, ids, caches, pads)
            k = key
            k, sub = jax.random.split(k)
            token = _select_token(last_logits, cfg, sub)
            done = np.zeros(B, bool)
            decode_sp = _tracing.begin_span(
                "generation.decode", cat="generation",
                args={"B": B, "N": cfg.max_new_tokens})
            try:
                for i in range(cfg.max_new_tokens):
                    if i > 0:
                        k, sub = jax.random.split(k)
                        # pos as a traced scalar: one compiled step
                        # executable for all tokens
                        token, caches = step(pb, token, caches,
                                             jnp.asarray(S + i - 1, jnp.int32),
                                             sub, pads)
                    tok_np = np.asarray(token).astype(np.int32)
                    if cfg.eos_token_id is not None:
                        tok_np = np.where(done, cfg.eos_token_id, tok_np)
                        done |= tok_np == cfg.eos_token_id
                    yield tok_np
                    if cfg.eos_token_id is not None and done.all():
                        return
            finally:
                _tracing.end_span(decode_sp)

    # recompile-monitor attribution: prefill/step/whole-program compiles
    # charge to this entry; a compile after the first completed generate
    # (new B/S/N or config) is surfaced as a retrace
    if stream:
        return python_token_iter()

    # perf-ledger item accounting: generated tokens per entry call, so
    # the ledger can report bytes/token and tokens/s for this entry
    from .observability import perf as _perf

    with _entrypoint("generation.generate"):
        if loop_mode == "scan" and cfg.max_new_tokens > 1:
            # one span for the whole fused program: prefill + decode are
            # a single dispatch in scan mode, host-side phases don't exist
            with _tracing.span("generation.generate", cat="generation",
                               args={"B": B, "S": S,
                                     "N": cfg.max_new_tokens,
                                     "mode": "scan"}):
                out = Tensor(generate_program(pb, ids, key, pads))
            _perf.note_entry_items("generation.generate",
                                   B * cfg.max_new_tokens)
            return out

        if cfg.eos_token_id is not None:
            # early-exit python loop: host-syncs each token (the
            # streaming path already pays that), stops once every row is
            # done, pads the tail back to N with EOS
            toks = list(python_token_iter())
            _perf.note_entry_items("generation.generate", B * len(toks))
            gen = np.stack(toks, axis=1)
            if gen.shape[1] < cfg.max_new_tokens:
                pad = np.full((B, cfg.max_new_tokens - gen.shape[1]),
                              cfg.eos_token_id, np.int32)
                gen = np.concatenate([gen, pad], axis=1)
            return Tensor(jnp.concatenate(
                [ids, jnp.asarray(gen)], axis=1))

        with _tracing.span("generation.prefill", cat="generation",
                           args={"B": B, "S": S}):
            caches = make_caches()
            last_logits, caches = prefill(pb, ids, caches, pads)
        key, sub = jax.random.split(key)
        token = _select_token(last_logits, cfg, sub)

        with _tracing.span("generation.decode", cat="generation",
                           args={"B": B, "N": cfg.max_new_tokens}):
            out = [token]
            for i in range(1, cfg.max_new_tokens):
                key, sub = jax.random.split(key)
                # pos as a traced scalar: one compiled step executable for all tokens
                token, caches = step(pb, token, caches, jnp.asarray(S + i - 1, jnp.int32), sub, pads)
                out.append(token)
            gen = jnp.stack(out, axis=1)  # [B, N]
        _perf.note_entry_items("generation.generate", B * cfg.max_new_tokens)
        return Tensor(jnp.concatenate([ids, gen], axis=1))


# retrace warnings for the generate entry cite this definition
from .observability.recompile import \
    register_entry_location as _register_entry  # noqa: E402

_register_entry("generation.generate", generate)
