"""Autoregressive text generation with a static KV cache.

Parity: the reference ecosystem's generation loop (PaddleNLP
generation_utils / paddle.incubate fused generation ops — greedy, top-k,
top-p sampling over cache_kv). TPU design: the KV cache is a set of
pre-allocated fixed-shape buffers updated with
``lax.dynamic_update_slice`` so the whole decode step is ONE jitted
program (static shapes, no per-token recompilation); the prompt is
prefilled in a single batched forward, then the token loop drives the
cached step executable.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .core.autograd import no_grad
from .core.tensor import Tensor
from .observability import tracing as _tracing
from .observability.recompile import entrypoint as _entrypoint
from .utils.functional import functional_call

__all__ = ["GenerationConfig", "generate", "generate_uncached",
           "update_static_kv_cache", "make_kv_caches", "make_cached_runner",
           "select_tokens", "split_keys", "make_paged_kv_pools",
           "paged_kv_cache_write", "gather_paged_kv"]


def _is_per_row(position_offset) -> bool:
    """True when ``position_offset`` is a per-row [B] vector (the serving
    engine's continuous-batching decode, where every slot sits at its own
    sequence position) rather than a shared scalar."""
    return getattr(position_offset, "ndim", 0) == 1


def kv_cache_write(buf, new, position_offset):
    """Write a step's [b, s, h, d] block into a pre-allocated
    [b, max_len, h, d] cache buffer at ``position_offset`` (the
    TPU-native dynamic_update_slice form of the reference's cache_kv
    write; one of the two halves of ``update_static_kv_cache``).

    ``position_offset`` may be a shared scalar (whole-batch decode) or a
    per-row [b] vector (slot-batched serving decode) — the vector form
    vmaps the update so each row lands at its own position."""
    from .ops.dispatch import apply_op, ensure_tensor

    def upd(b, n):
        if _is_per_row(position_offset):
            return jax.vmap(
                lambda br, nr, off: jax.lax.dynamic_update_slice(
                    br, nr.astype(br.dtype), (off, 0, 0))
            )(b, n, position_offset)
        return jax.lax.dynamic_update_slice(
            b, n.astype(b.dtype), (0, position_offset, 0, 0))

    return apply_op("kv_cache_update", upd, ensure_tensor(buf),
                    ensure_tensor(new))


def _causal_cache_mask(position_offset, s: int, max_len: int) -> Tensor:
    """The additive causal mask over a static cache of ``max_len`` key
    positions for ``s`` query tokens starting at ``position_offset`` —
    shared by the contiguous and paged cache paths so both build the
    bit-identical mask (the engine's parity oracle depends on it)."""
    kpos = jnp.arange(max_len)
    if _is_per_row(position_offset):
        po = position_offset
        qpos = po[:, None] + jnp.arange(s)          # [b, s]
        m = (kpos[None, None, :] <= qpos[:, :, None]) \
            & (kpos[None, None, :] < (po[:, None, None] + s))
        return Tensor(jnp.where(m[:, None], 0.0, -1e30).astype(jnp.float32))
    qpos = position_offset + jnp.arange(s)
    m = (kpos[None, :] <= qpos[:, None]) & (kpos[None, :] < position_offset + s)
    return Tensor(jnp.where(m[None, None], 0.0, -1e30).astype(jnp.float32))


def make_paged_kv_pools(config, num_blocks: int, block_size: int, dtype):
    """Device-resident paged KV pools: a list (one per decoder layer) of
    {"k", "v"} jnp arrays shaped [num_blocks, block_size,
    num_key_value_heads, head_dim]. Slots address the pool through
    per-slot int32 block tables instead of owning contiguous rows, so
    HBM is bounded by TOKENS IN FLIGHT, not slots * worst-case length."""
    n_kv = config.num_key_value_heads
    head_dim = config.hidden_size // config.num_attention_heads
    return [{"k": jnp.zeros((num_blocks, block_size, n_kv, head_dim), dtype),
             "v": jnp.zeros((num_blocks, block_size, n_kv, head_dim), dtype)}
            for _ in range(config.num_hidden_layers)]


def paged_kv_cache_write(pool, new, block_table, position_offset,
                         valid_len=None):
    """Scatter a step's [b, s, h, d] K-or-V block into the shared
    [num_blocks, block_size, h, d] pool through per-row block tables
    (the paged analogue of ``kv_cache_write``): token j of row b lands
    in physical block ``block_table[b, (pos_b + j) // block_size]`` at
    offset ``(pos_b + j) % block_size``.

    ``valid_len`` (scalar or per-row [b]) caps how many of the ``s``
    tokens are real: padded tail tokens (chunked prefill pads the last
    chunk to the fixed chunk shape) are routed into the reserved dump
    block 0 so they can never dirty a live block."""
    from .ops.dispatch import apply_op, ensure_tensor

    bt = block_table._data if isinstance(block_table, Tensor) \
        else jnp.asarray(block_table)
    po = position_offset._data if isinstance(position_offset, Tensor) \
        else position_offset
    vl = None if valid_len is None else (
        valid_len._data if isinstance(valid_len, Tensor) else valid_len)

    def upd(p, n):
        num_blocks, bs = p.shape[0], p.shape[1]
        b, s = n.shape[0], n.shape[1]
        pos = jnp.asarray(po, jnp.int32)
        if pos.ndim == 0:
            pos = jnp.broadcast_to(pos, (b,))
        tpos = pos[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
        blk = jnp.clip(tpos // bs, 0, bt.shape[1] - 1)
        phys = jnp.take_along_axis(jnp.asarray(bt, jnp.int32), blk, axis=1)
        idx = phys * bs + tpos % bs                      # [b, s] flat
        if vl is not None:
            va = jnp.asarray(vl, jnp.int32)
            if va.ndim == 0:
                va = jnp.broadcast_to(va, (b,))
            # pad tokens -> flat slot 0 (dump block 0, offset 0)
            idx = jnp.where(tpos < (pos + va)[:, None], idx, 0)
        flat = p.reshape((num_blocks * bs,) + p.shape[2:])
        flat = flat.at[idx.reshape(-1)].set(
            n.astype(p.dtype).reshape((b * s,) + n.shape[2:]))
        return flat.reshape(p.shape)

    return apply_op("paged_kv_cache_update", upd, ensure_tensor(pool),
                    ensure_tensor(new))


def gather_paged_kv(pool, block_table):
    """Materialize a slot-major [b, nb*block_size, h, d] view of the
    paged pool through the block tables — the XLA fallback read path
    (CPU lane / kernel-ineligible shapes). Logically identical to the
    contiguous [b, max_len, h, d] cache: positions past a row's length
    hold whatever the pool holds there, exactly like the contiguous
    cache holds zeros — both are exact no-ops under the additive
    causal mask."""
    from .ops.dispatch import apply_op, ensure_tensor

    bt = block_table._data if isinstance(block_table, Tensor) \
        else jnp.asarray(block_table)

    def g(p):
        out = jnp.take(p, jnp.asarray(bt, jnp.int32), axis=0)
        b, nb, bs = out.shape[0], out.shape[1], out.shape[2]
        return out.reshape((b, nb * bs) + p.shape[2:])

    return apply_op("paged_kv_gather", g, ensure_tensor(pool))


def _update_paged_kv_cache(kv_cache: dict, k, v, position_offset,
                           build_mask: bool, gather: bool):
    """Paged half of ``update_static_kv_cache``: scatter the step's k/v
    through the block table, then either gather the slot-major view for
    the XLA attention paths (``gather=True``) or hand the raw pools back
    for the paged Pallas kernel (``gather=False``)."""
    bt = kv_cache["bt"]
    valid = kv_cache.get("valid")
    ck = paged_kv_cache_write(kv_cache["k"], k, bt, position_offset, valid)
    cv = paged_kv_cache_write(kv_cache["v"], v, bt, position_offset, valid)
    new_cache = dict(kv_cache)
    new_cache["k"] = ck
    new_cache["v"] = cv
    bt_arr = bt._data if isinstance(bt, Tensor) else bt
    bs = int(ck._data.shape[1] if isinstance(ck, Tensor) else ck.shape[1])
    max_len = int(bt_arr.shape[1]) * bs
    mask = _causal_cache_mask(position_offset, k.shape[1], max_len) \
        if build_mask else None
    if gather:
        return (gather_paged_kv(ck, bt), gather_paged_kv(cv, bt),
                new_cache, mask)
    return ck, cv, new_cache, mask


def update_static_kv_cache(kv_cache: dict, k, v, position_offset,
                           build_mask: bool = True, gather: bool = True):
    """The static-cache protocol shared by the decoder models (llama/
    gpt): write this step's k/v [b, s, h, d] into the pre-allocated
    [b, max_len, h, d] buffers at ``position_offset`` and (unless the
    caller brings its own attn_mask — ``build_mask=False``) build the
    additive causal mask exposing only positions < offset + s.
    Returns (k_full, v_full, new_cache, mask_or_None).

    A per-row [b] ``position_offset`` vector produces per-row writes and
    a per-row [b, 1, s, max_len] mask (slots at different positions in
    one batch — the serving engine's decode step).

    PAGED caches (dict carries a ``"bt"`` block table, pools shaped
    [num_blocks, block_size, h, d]) scatter the write through the table
    instead; ``gather=True`` additionally materializes the slot-major
    [b, nb*block_size, h, d] view for the XLA attention fallbacks, while
    ``gather=False`` (the paged-kernel path, which reads the pool
    directly) skips that copy and returns the raw pools as (k, v)."""
    if isinstance(kv_cache, dict) and "bt" in kv_cache:
        return _update_paged_kv_cache(kv_cache, k, v, position_offset,
                                      build_mask, gather)
    ck = kv_cache_write(kv_cache["k"], k, position_offset)
    cv = kv_cache_write(kv_cache["v"], v, position_offset)
    mask = None
    if build_mask:
        s = k.shape[1]
        max_len = int(ck._data.shape[1] if isinstance(ck, Tensor) else ck.shape[1])
        mask = _causal_cache_mask(position_offset, s, max_len)
    return ck, cv, {"k": ck, "v": cv}, mask


def _mask_after_eos(gen, eos_id):
    """Replace everything after the first EOS with EOS (post-hoc, static)."""
    is_eos = gen == eos_id
    seen = jnp.cumsum(is_eos.astype(jnp.int32), axis=1) - is_eos.astype(jnp.int32)
    return jnp.where(seen > 0, eos_id, gen)


@dataclass
class GenerationConfig:
    max_new_tokens: int = 32
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    seed: int = 0


def _select_token(logits, cfg: GenerationConfig, key):
    """logits [B, V] -> next token [B]."""
    if not cfg.do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(cfg.temperature, 1e-6)
    if cfg.top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -cfg.top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    if cfg.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest logit value still inside the nucleus
        inside = cum - probs < cfg.top_p
        cutoff = jnp.min(jnp.where(inside, sorted_logits, jnp.inf), axis=-1, keepdims=True)
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def split_keys(keys):
    """Per-row PRNG advance: [B, 2] keys -> (new_keys [B, 2], subkeys
    [B, 2]), each row exactly ``jax.random.split(key)`` for that row —
    so a slot's key chain inside a batched decode step reproduces the
    ``key, sub = jax.random.split(key)`` chain ``generate`` drives for a
    single request."""
    pairs = jax.vmap(jax.random.split)(keys)  # [B, 2, 2]
    return pairs[:, 0], pairs[:, 1]


# Bounded-nucleus fast path for select_tokens: a full-vocab XLA sort is
# by far the most expensive op in a decode step (CPU: ~8x a
# lax.top_k(256) on a [4, 4096] batch), so rows whose top-k filter fits
# this bound take a top_k-only path. The fallback keeps it EXACT — see
# select_tokens.
_NUCLEUS_BOUND = 256


def select_tokens(logits, keys, do_sample, temperature, top_k, top_p):
    """Per-row token selection with TRACED sampling params: [B, V]
    logits -> [B] tokens, where each row carries its own ``do_sample`` /
    ``temperature`` / ``top_k`` / ``top_p`` / PRNG key. Mixed greedy and
    sampled requests therefore share ONE compiled step program (the
    serving engine's requirement); row-wise the math is exactly
    ``_select_token`` on that row alone, so a slot's tokens match a
    standalone ``generate`` call with the same config and key chain.

    ``top_k <= 0`` and ``top_p >= 1.0`` disable their filters per row
    (same semantics as the static config path).

    Bit-exactness of the fast path: when every sampled row has
    ``0 < top_k <= _NUCLEUS_BOUND`` (and no tie straddles the bound),
    the kept set lives entirely in the top-K values, so padding those
    back to width V with -1e30 reproduces the EXACT masked-sorted array
    the full-sort path builds — every downstream softmax/cumsum/cutoff
    runs on an identical array and is bit-identical, whatever the
    backend's reduction groupings. Any row outside that envelope
    (top-p-only sampling, huge top_k, boundary ties) flips a runtime
    ``lax.cond`` to the full sort."""
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    lg = logits / jnp.maximum(temperature, 1e-6)[:, None]
    K = min(_NUCLEUS_BOUND, V)

    def _filter(sorted_desc):
        """Width-V filter math given the descending-sorted logits:
        top-k threshold at the k-th largest, then the top-p nucleus
        over the k-filtered distribution (the single-sort form: the
        'sorted filtered' array is the sorted array with the < kth
        suffix dropped to -1e30, since filtering keeps a prefix)."""
        kth_idx = jnp.clip(jnp.minimum(top_k, V) - 1, 0, V - 1).astype(jnp.int32)
        kth = jnp.take_along_axis(sorted_desc, kth_idx[:, None], axis=-1)
        kfilt = (top_k > 0)[:, None]
        out = jnp.where(kfilt & (lg < kth), -1e30, lg)
        sd = jnp.where(kfilt & (sorted_desc < kth), -1e30, sorted_desc)
        probs = jax.nn.softmax(sd, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        inside = cum - probs < top_p[:, None]
        cutoff = jnp.min(jnp.where(inside, sd, jnp.inf), axis=-1,
                         keepdims=True)
        return jnp.where((top_p < 1.0)[:, None] & (out < cutoff), -1e30, out)

    tops = jax.lax.top_k(lg, K)[0]  # [B, K], descending
    padded = jnp.concatenate(
        [tops, jnp.full((B, V - K), -1e30, lg.dtype)], axis=-1)
    # Everything downstream reads ``padded`` through an optimization
    # barrier, NEVER ``tops``: lax.top_k lowers to sort+slice, which
    # XLA:CPU pattern-matches into a fast partial-sort TopK custom call
    # — but slicing the result again gets algebraically pushed back
    # into slice-of-sort, breaking the match and silently falling back
    # to a full-vocab sort (~7x this op's cost). The barrier pins the
    # concat as a materialization point so consumers can't sink
    # through it.
    padded = jax.lax.optimization_barrier(padded)
    kth_idx = jnp.clip(jnp.minimum(top_k, K) - 1, 0, K - 1).astype(jnp.int32)
    kth = jnp.take_along_axis(padded, kth_idx[:, None], axis=-1)
    # strict: values beyond the bound are all < kth, so the kept set
    # (lg >= kth) is fully inside the top-K — no tie straddles the edge
    row_fast = (~do_sample) | ((top_k > 0) & (top_k <= K)
                               & (padded[:, K - 1] < kth[:, 0]))
    lg = jax.lax.cond(
        jnp.all(row_fast),
        lambda: _filter(padded),
        lambda: _filter(jnp.sort(lg, axis=-1)[:, ::-1]))
    # per-row categorical with that row's key: the flat random-bit draw
    # for a [V] row equals the [1, V] draw generate makes at B=1
    sampled = jax.vmap(lambda k, l: jax.random.categorical(k, l))(
        keys, lg).astype(jnp.int32)
    return jnp.where(do_sample, sampled, greedy)


def make_kv_caches(config, batch_size: int, max_len: int, dtype):
    """Pre-allocated per-layer static KV buffers: a list (one per
    decoder layer) of {"k", "v"} jnp arrays shaped
    [batch_size, max_len, num_key_value_heads, head_dim]."""
    n_kv = config.num_key_value_heads
    head_dim = config.hidden_size // config.num_attention_heads
    return [{"k": jnp.zeros((batch_size, max_len, n_kv, head_dim), dtype),
             "v": jnp.zeros((batch_size, max_len, n_kv, head_dim), dtype)}
            for _ in range(config.num_hidden_layers)]


def make_cached_runner(model):
    """The jit-friendly functional cached forward shared by ``generate``
    and the serving engine: ``run(pb, token_ids, caches, pos,
    attn_mask=None)`` calls the model with parameters/buffers supplied
    as the ``pb`` pytree and raw-jnp caches, returning
    (logits_jnp, new_caches_jnp). ``pos`` may be a python int, a traced
    scalar, or a per-row [B] vector (serving decode)."""

    def run(pb, token_ids, caches, pos, attn_mask=None):
        with no_grad():
            # wrap every array entry (k/v buffers, and for paged caches
            # the bt/valid companions) so the cache dict round-trips the
            # model as plain Tensors
            caches_t = [{kk: vv if isinstance(vv, Tensor) else Tensor(vv)
                         for kk, vv in c.items()} for c in caches]
            am = None
            if attn_mask is not None:
                am = attn_mask if isinstance(attn_mask, Tensor) else Tensor(attn_mask)
            logits, new_caches = functional_call(
                model, pb, Tensor(token_ids), attn_mask=am,
                kv_caches=caches_t, position_offset=pos)
        return (logits._data,
                [{kk: vv._data if isinstance(vv, Tensor) else vv
                  for kk, vv in c.items()} for c in new_caches])

    return run


def generate_uncached(model, input_ids, max_new_tokens: int = 32, do_sample: bool = False,
                      temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
                      eos_token_id: Optional[int] = None, seed: int = 0) -> Tensor:
    """Fallback decode for models without KV-cache plumbing: re-runs the
    full forward per token. Correct but O(n^2) — the cached path in
    ``generate`` is the serving path (llama and gpt both plumb it)."""
    cfg = GenerationConfig(max_new_tokens, do_sample, temperature, top_k, top_p,
                           eos_token_id, seed)
    ids = input_ids._data if isinstance(input_ids, Tensor) else jnp.asarray(input_ids)
    ids = ids.astype(jnp.int32)
    S = ids.shape[1]
    max_pos = getattr(model.config, "max_position_embeddings", None)
    if max_pos is not None and S + cfg.max_new_tokens > max_pos:
        raise ValueError(
            f"prompt ({S}) + max_new_tokens ({cfg.max_new_tokens}) exceeds "
            f"max_position_embeddings ({max_pos})")
    if cfg.max_new_tokens <= 0:
        return Tensor(ids)
    key = jax.random.PRNGKey(cfg.seed)
    with no_grad():
        for _ in range(cfg.max_new_tokens):
            logits = model(Tensor(ids))
            key, sub = jax.random.split(key)
            nxt = _select_token(logits._data[:, -1], cfg, sub)
            ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    if cfg.eos_token_id is not None:
        gen = _mask_after_eos(ids[:, S:], cfg.eos_token_id)
        ids = jnp.concatenate([ids[:, :S], gen], axis=1)
    return Tensor(ids)


def _normalize_prompts(input_ids, pad_token_id):
    """Normalize ``input_ids`` into (ids [B, S] int32, pad_lens or None).

    Accepts a [B, S] Tensor/array (classic equal-length prompts) or a
    ragged list/tuple of per-row token sequences. Ragged rows are
    LEFT-padded with ``pad_token_id`` to the longest prompt, and
    ``pad_lens`` [B] counts each row's leading pads so prefill/decode
    can mask them out of attention. A rectangular input combined with an
    explicit ``pad_token_id`` also enters ragged mode: leading
    ``pad_token_id`` tokens per row are treated as padding."""
    if isinstance(input_ids, (list, tuple)) and input_ids and \
            isinstance(input_ids[0], (list, tuple, np.ndarray)):
        rows = [np.asarray(r, dtype=np.int32).reshape(-1) for r in input_ids]
        lens = [r.shape[0] for r in rows]
        if any(l == 0 for l in lens):
            raise ValueError("empty prompt in ragged batch")
        S = max(lens)
        if len(set(lens)) > 1 and pad_token_id is None:
            raise ValueError(
                "ragged prompts (lengths %s) require pad_token_id for "
                "left-padding" % sorted(set(lens)))
        ids = np.full((len(rows), S), pad_token_id if pad_token_id is not None
                      else 0, np.int32)
        for b, r in enumerate(rows):
            ids[b, S - r.shape[0]:] = r
        if pad_token_id is None:
            return jnp.asarray(ids), None
        pad_lens = np.asarray([S - l for l in lens], np.int32)
        return jnp.asarray(ids), pad_lens
    ids = input_ids._data if isinstance(input_ids, Tensor) else jnp.asarray(input_ids)
    ids = ids.astype(jnp.int32)
    if pad_token_id is None:
        return ids, None
    arr = np.asarray(ids)
    # leading-run-of-pads per row (a pad id INSIDE the prompt is content)
    is_pad = arr == pad_token_id
    pad_lens = (np.cumprod(is_pad, axis=1)).sum(axis=1).astype(np.int32)
    pad_lens = np.minimum(pad_lens, arr.shape[1] - 1)  # never mask a whole row
    return ids, pad_lens


def generate(model, input_ids, max_new_tokens: int = 32, do_sample: bool = False,
             temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
             eos_token_id: Optional[int] = None, seed: int = 0,
             loop_mode: str = "scan", pad_token_id: Optional[int] = None,
             stream: bool = False):
    """Generate continuations for ``input_ids`` [B, S]; returns [B, S+N].

    Greedy by default; sampling with temperature/top-k/top-p when
    ``do_sample``. Stops early only via post-hoc masking (static shapes).

    ``loop_mode="scan"`` (default) compiles the WHOLE decode loop into one
    program (``lax.scan`` over the token index) — one dispatch for N
    tokens, which is what makes decode fast over a remote PJRT transport;
    ``"python"`` drives one jitted step per token (useful for streaming
    consumers that want tokens as they land). In python mode with an
    ``eos_token_id`` the token loop exits as soon as every row has
    emitted EOS (the result is padded back to [B, S+N] with EOS, so the
    output contract is unchanged).

    Ragged prompts: pass a list of per-row token sequences (or a
    pre-padded [B, S] batch) together with ``pad_token_id`` — rows are
    LEFT-padded and an attention mask hides the pads through prefill AND
    every decode step. Pad positions keep their absolute cache/RoPE
    indices: RoPE scores depend only on relative distance, so a
    left-padded row decodes exactly like its unpadded twin (for learned
    position embeddings the shift is absolute, like other left-padding
    implementations).

    ``stream=True`` (forces python mode) returns a generator that yields
    one np.int32 [B] token vector per generated position as it lands
    (EOS-masked rows keep yielding EOS) and stops early once every row
    is done."""
    cfg = GenerationConfig(max_new_tokens, do_sample, temperature, top_k, top_p,
                           eos_token_id, seed)
    ids, pad_lens = _normalize_prompts(input_ids, pad_token_id)
    ragged = pad_lens is not None
    B, S = ids.shape
    max_len = S + cfg.max_new_tokens
    config = model.config
    if max_len > config.max_position_embeddings:
        raise ValueError(
            f"prompt ({S}) + max_new_tokens ({cfg.max_new_tokens}) exceeds "
            f"max_position_embeddings ({config.max_position_embeddings}); the "
            "position table (RoPE / learned embeddings) has no entries past "
            "that position")
    dtype = next(iter(model.parameters()))._data.dtype

    params = {k: v._data for k, v in model.named_parameters_dict().items()}
    buffers = {k: v._data for k, v in model.named_buffers_dict().items()}

    def make_caches():
        return make_kv_caches(config, B, max_len, dtype)

    base_run = make_cached_runner(model)

    def run(pb, token_ids, caches, pos, pads=None):
        if pads is None:
            return base_run(pb, token_ids, caches, pos)
        # ragged: causal mask that ALSO hides each row's left pads, for
        # prefill and for every decode step (pads live at cache positions
        # 0..pad_len-1 forever, so the default causal mask would attend
        # them)
        s = token_ids.shape[1]
        kpos = jnp.arange(max_len)
        qpos = pos + jnp.arange(s)
        m = (kpos[None, :] <= qpos[:, None]) & (kpos[None, :] < pos + s)
        m = m[None] & (kpos[None, None, :] >= pads[:, None, None])
        mask = jnp.where(m[:, None], 0.0, -1e30).astype(jnp.float32)
        return base_run(pb, token_ids, caches, pos, attn_mask=mask)

    if stream:
        loop_mode = "python"
    if loop_mode not in ("scan", "python"):
        raise ValueError(f"loop_mode must be 'scan' or 'python', got {loop_mode!r}")
    if cfg.max_new_tokens <= 0:
        if stream:
            return iter(())
        return Tensor(ids)

    # jitted executables are cached on the model so repeat generate() calls
    # with the same shapes/config reuse the compiled programs; the KV cache
    # pytree is donated so decode updates buffers in place
    # eos only shapes the scan-mode whole-generate program; python-mode
    # executables are eos-independent (masking happens outside jit) and
    # must not recompile per eos id
    # the flash-decode env gate is a python-side dispatch baked into the
    # trace: flipping it must not reuse executables traced the other way
    from .pallas_kernels.decode_attention import flash_decode_enabled

    gen_key = (B, S, cfg.max_new_tokens, cfg.do_sample, cfg.temperature,
               cfg.top_k, cfg.top_p,
               cfg.eos_token_id if loop_mode == "scan" else None, loop_mode,
               ragged, flash_decode_enabled())
    cache_store = model.__dict__.setdefault("_generate_jit_cache", {})
    if gen_key not in cache_store:

        @jax.jit
        def prefill(pb, ids, caches, pads):
            logits, caches = run(pb, ids, caches, 0, pads)
            return logits[:, -1], caches

        @functools.partial(jax.jit, donate_argnums=(2,))
        def step(pb, token, caches, pos, key, pads):
            logits, caches = run(pb, token[:, None], caches, pos, pads)
            nxt = _select_token(logits[:, 0], cfg, key)
            return nxt, caches

        @jax.jit
        def generate_program(pb, ids, key, pads):
            """The WHOLE generate as ONE program: cache init + prefill +
            first-token select + (N-1)-step ``lax.scan`` decode + EOS
            masking + prompt concat. A single dispatch and a single
            result transfer — per-token (or even per-phase) python
            dispatch dominates end-to-end latency on a remote PJRT
            transport (measured 3.2s -> 0.5s for 16x256 tokens on the
            134M model over the axon tunnel)."""
            caches = make_caches()
            logits, caches = run(pb, ids, caches, 0, pads)
            key, sub = jax.random.split(key)
            token = _select_token(logits[:, -1], cfg, sub)

            def body(carry, i):
                token, caches, key = carry
                key, sub = jax.random.split(key)
                logits, caches = run(pb, token[:, None], caches, S + i, pads)
                nxt = _select_token(logits[:, 0], cfg, sub)
                return (nxt, caches, key), nxt

            (_, caches, _), toks = jax.lax.scan(
                body, (token, caches, key),
                jnp.arange(cfg.max_new_tokens - 1, dtype=jnp.int32))
            gen = jnp.concatenate([token[:, None], jnp.swapaxes(toks, 0, 1)],
                                  axis=1)  # [B, N]
            if cfg.eos_token_id is not None:
                gen = _mask_after_eos(gen, cfg.eos_token_id)
            return jnp.concatenate([ids, gen], axis=1)

        cache_store[gen_key] = (prefill, step, generate_program)
    prefill, step, generate_program = cache_store[gen_key]

    pb = {**params, **buffers}
    key = jax.random.PRNGKey(cfg.seed)
    pads = jnp.asarray(pad_lens) if ragged else None

    def python_token_iter():
        """One jitted step per token; yields the np.int32 [B] token
        vector per position, EOS-masked, exiting early once every row
        has emitted EOS."""
        with _entrypoint("generation.generate"):
            with _tracing.span("generation.prefill", cat="generation",
                               args={"B": B, "S": S}):
                caches = make_caches()
                last_logits, caches = prefill(pb, ids, caches, pads)
            k = key
            k, sub = jax.random.split(k)
            token = _select_token(last_logits, cfg, sub)
            done = np.zeros(B, bool)
            decode_sp = _tracing.begin_span(
                "generation.decode", cat="generation",
                args={"B": B, "N": cfg.max_new_tokens})
            try:
                for i in range(cfg.max_new_tokens):
                    if i > 0:
                        k, sub = jax.random.split(k)
                        # pos as a traced scalar: one compiled step
                        # executable for all tokens
                        token, caches = step(pb, token, caches,
                                             jnp.asarray(S + i - 1, jnp.int32),
                                             sub, pads)
                    tok_np = np.asarray(token).astype(np.int32)
                    if cfg.eos_token_id is not None:
                        tok_np = np.where(done, cfg.eos_token_id, tok_np)
                        done |= tok_np == cfg.eos_token_id
                    yield tok_np
                    if cfg.eos_token_id is not None and done.all():
                        return
            finally:
                _tracing.end_span(decode_sp)

    # recompile-monitor attribution: prefill/step/whole-program compiles
    # charge to this entry; a compile after the first completed generate
    # (new B/S/N or config) is surfaced as a retrace
    if stream:
        return python_token_iter()

    with _entrypoint("generation.generate"):
        if loop_mode == "scan" and cfg.max_new_tokens > 1:
            # one span for the whole fused program: prefill + decode are
            # a single dispatch in scan mode, host-side phases don't exist
            with _tracing.span("generation.generate", cat="generation",
                               args={"B": B, "S": S,
                                     "N": cfg.max_new_tokens,
                                     "mode": "scan"}):
                return Tensor(generate_program(pb, ids, key, pads))

        if cfg.eos_token_id is not None:
            # early-exit python loop: host-syncs each token (the
            # streaming path already pays that), stops once every row is
            # done, pads the tail back to N with EOS
            toks = list(python_token_iter())
            gen = np.stack(toks, axis=1)
            if gen.shape[1] < cfg.max_new_tokens:
                pad = np.full((B, cfg.max_new_tokens - gen.shape[1]),
                              cfg.eos_token_id, np.int32)
                gen = np.concatenate([gen, pad], axis=1)
            return Tensor(jnp.concatenate(
                [ids, jnp.asarray(gen)], axis=1))

        with _tracing.span("generation.prefill", cat="generation",
                           args={"B": B, "S": S}):
            caches = make_caches()
            last_logits, caches = prefill(pb, ids, caches, pads)
        key, sub = jax.random.split(key)
        token = _select_token(last_logits, cfg, sub)

        with _tracing.span("generation.decode", cat="generation",
                           args={"B": B, "N": cfg.max_new_tokens}):
            out = [token]
            for i in range(1, cfg.max_new_tokens):
                key, sub = jax.random.split(key)
                # pos as a traced scalar: one compiled step executable for all tokens
                token, caches = step(pb, token, caches, jnp.asarray(S + i - 1, jnp.int32), sub, pads)
                out.append(token)
            gen = jnp.stack(out, axis=1)  # [B, N]
        return Tensor(jnp.concatenate([ids, gen], axis=1))
