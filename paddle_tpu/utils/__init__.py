from . import functional


def run_check():
    """Sanity-check the installation (parity: paddle.utils.run_check) —
    runs a tiny train step on the default device and, when several devices
    are visible, a data-parallel step over all of them."""
    import jax
    import numpy as np

    import paddle_tpu as paddle
    from .. import nn

    print(f"Running verify on backend={jax.default_backend()}, "
          f"devices={len(jax.devices())} ...")
    paddle.seed(0)
    lin = nn.Linear(4, 4)
    x = paddle.to_tensor(np.ones((2, 4), "float32"), stop_gradient=False)
    loss = (lin(x) ** 2).mean()
    loss.backward()
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())
    opt.step()
    assert np.isfinite(float(loss.numpy()))
    n = len(jax.devices())
    if n > 1:
        import paddle_tpu.distributed as dist
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = dist.ProcessMesh(np.arange(n), ["dp"])
        arr = jax.device_put(np.ones((n * 2, 4), "float32"),
                             NamedSharding(mesh.jax_mesh, PartitionSpec("dp")))
        out = (arr @ np.ones((4, 1), "float32")).sum()
        assert np.isfinite(float(out))
        print(f"paddle_tpu works on {n} devices.")
    print("paddle_tpu is installed successfully!")
