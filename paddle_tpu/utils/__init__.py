from . import functional
