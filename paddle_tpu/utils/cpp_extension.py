"""Custom C++ op extension builder.

Parity: python/paddle/utils/cpp_extension/ (load/setup/CppExtension — the
JIT build path of custom operators, reference
fluid/eager/custom_operator/). TPU design: the C++ side is a plain
C-ABI function over host buffers, compiled with g++ into a shared lib;
the framework side wraps it with ``jax.pure_callback`` so the custom op
participates in jit programs (XLA calls back to host for this op —
matching the reference's host-side custom op execution), and with
``apply_op`` so it lands on the autograd tape when a backward function
is registered.

C ABI convention (simplified PD_BUILD_OP):
    extern "C" void <op>(const float** ins, float* out, const int64_t* shape,
                         int ndim);
for single-output float ops; the Python wrapper handles marshalling.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops.dispatch import apply_op

__all__ = ["load", "CppExtension", "CustomOpModule", "get_build_directory"]


def get_build_directory() -> str:
    d = os.environ.get("PADDLE_EXTENSION_DIR",
                       os.path.join(tempfile.gettempdir(), "paddle_tpu_extensions"))
    os.makedirs(d, exist_ok=True)
    return d


class CppExtension:
    """Parity: paddle.utils.cpp_extension.CppExtension(sources=...)."""

    def __init__(self, sources: Sequence[str], extra_compile_args: Optional[List[str]] = None,
                 **kwargs):
        self.sources = list(sources)
        self.extra_compile_args = list(extra_compile_args or [])


def _compile(name: str, sources: Sequence[str], extra_cxx_cflags: Sequence[str],
             build_directory: Optional[str], verbose: bool) -> str:
    build_dir = build_directory or get_build_directory()
    os.makedirs(build_dir, exist_ok=True)
    stamp = hashlib.sha1()
    for s in sources:
        with open(s, "rb") as f:
            stamp.update(f.read())
    stamp.update(" ".join(extra_cxx_cflags).encode())
    so_path = os.path.join(build_dir, f"{name}_{stamp.hexdigest()[:12]}.so")
    if not os.path.exists(so_path):
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
               *extra_cxx_cflags, *sources, "-o", so_path]
        if verbose:
            print("[cpp_extension]", " ".join(cmd))
        subprocess.run(cmd, check=True, capture_output=not verbose)
    return so_path


class CustomOpModule:
    """Loaded extension: exposes each C symbol as a framework op."""

    def __init__(self, name: str, so_path: str):
        self.name = name
        self.so_path = so_path
        self._lib = ctypes.CDLL(so_path)
        self._grads: dict = {}

    def register_backward(self, op_name: str, grad_fn: Callable):
        """grad_fn(cotangent_arrays, input_arrays) -> tuple of input grads
        (host numpy). Registered ops become differentiable."""
        self._grads[op_name] = grad_fn

    def _call_c(self, op_name: str, arrays: List[np.ndarray], out_shape) -> np.ndarray:
        fn = getattr(self._lib, op_name)
        fn.restype = None
        ins = (ctypes.POINTER(ctypes.c_float) * len(arrays))()
        for i, a in enumerate(arrays):
            ins[i] = a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
        out = np.empty(out_shape, np.float32)
        shape_arr = (ctypes.c_int64 * max(len(out_shape), 1))(*(out_shape or (0,)))
        fn(ins, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
           shape_arr, ctypes.c_int(len(out_shape)))
        return out

    def __getattr__(self, op_name: str):
        if op_name.startswith("_"):
            raise AttributeError(op_name)

        def op(*tensors: Tensor, out_shape=None):
            shape = tuple(out_shape) if out_shape is not None else tuple(tensors[0].shape)

            def host(*arrays):
                np_in = [np.ascontiguousarray(np.asarray(a, np.float32)) for a in arrays]
                return self._call_c(op_name, np_in, shape)

            def fn(*arrays):
                return jax.pure_callback(
                    host, jax.ShapeDtypeStruct(shape, jnp.float32), *arrays)

            grad_fn = self._grads.get(op_name)
            if grad_fn is None:
                # no backward registered: forward works under autograd (vjp
                # needs a rule for the callback), backward raises — matching
                # the reference's "no grad kernel for custom op" error
                @jax.custom_vjp
                def nodiff_fn(*arrays):
                    return fn(*arrays)

                def _fwd(*arrays):
                    return fn(*arrays), None

                def _bwd(res, g):
                    raise NotImplementedError(
                        f"custom op {op_name} has no registered backward; "
                        "call register_backward() to make it differentiable")

                nodiff_fn.defvjp(_fwd, _bwd)
                return apply_op(f"custom_{op_name}", nodiff_fn, *tensors)

            @jax.custom_vjp
            def diff_fn(*arrays):
                return fn(*arrays)

            def fwd(*arrays):
                return fn(*arrays), arrays

            def bwd(res, g):
                in_sds = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype) for a in res)

                def host_grad(g, *arrays):
                    outs = grad_fn(np.asarray(g), [np.asarray(a) for a in arrays])
                    return tuple(np.asarray(o, np.float32) for o in outs)

                return jax.pure_callback(host_grad, in_sds, g, *res)

            diff_fn.defvjp(fwd, bwd)
            return apply_op(f"custom_{op_name}", diff_fn, *tensors)

        return op


def load(name: str, sources: Sequence[str], extra_cxx_cflags: Optional[Sequence[str]] = None,
         build_directory: Optional[str] = None, verbose: bool = False,
         **kwargs) -> CustomOpModule:
    """Compile + load a custom op extension (parity:
    paddle.utils.cpp_extension.load)."""
    so_path = _compile(name, sources, list(extra_cxx_cflags or []), build_directory, verbose)
    return CustomOpModule(name, so_path)
