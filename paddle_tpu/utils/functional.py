"""Functional execution of Layers: run a Layer with externally supplied
parameter/buffer arrays.

This is the bridge between the Paddle-shaped object API (mutable Layer
holding Parameters) and JAX's functional world (params as pytree inputs to
jit/pjit/grad). The static-graph reference equivalent is the
ProgramDesc/PIR partial program holding parameters as graph inputs
(reference: jit/dy2static/pir_partial_program.py).
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict

import jax

from ..core.tensor import Tensor


@contextlib.contextmanager
def _swapped_state(layer, state: Dict[str, Any]):
    """Temporarily replace Parameter/buffer payloads with the given arrays."""
    own = {}
    for name, t in layer.state_dict().items():
        own[name] = t
    saved = {}
    try:
        for name, value in state.items():
            if name in own:
                t = own[name]
                saved[name] = t._data
                t._data = value._data if isinstance(value, Tensor) else value
        yield
    finally:
        for name, data in saved.items():
            own[name]._data = data


def functional_call(layer, state: Dict[str, Any], *args, **kwargs):
    """Call ``layer(*args)`` with its parameters/buffers replaced by
    ``state`` (arrays or Tensors). Used by to_static and pjit train steps."""
    with _swapped_state(layer, state):
        return layer(*args, **kwargs)


def tree_arrays(state: Dict[str, Tensor]):
    return {k: (v._data if isinstance(v, Tensor) else v) for k, v in state.items()}
