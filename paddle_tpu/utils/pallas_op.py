"""Device-kernel custom ops: register a Pallas TPU kernel as a framework op.

Parity role: the reference's custom-op registration for DEVICE kernels
(paddle/fluid/eager/custom_operator/ + utils/cpp_extension building CUDA
kernels). On TPU the device-kernel language is Pallas, so a custom op is
a pallas_call-built jax function plus an optional custom backward — this
module wires both into the dispatch layer so the op gets AMP hooks, tape
recording, NaN checks, and to_static capture exactly like built-ins
(the host-callback path for CPU code lives in utils/cpp_extension.py).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax

from ..core.tensor import Tensor
from ..ops.dispatch import OP_REGISTRY, apply_op, ensure_tensor, register_op

__all__ = ["register_pallas_op", "get_custom_op"]

_CUSTOM_OPS = {}


def register_pallas_op(name: str, forward: Callable, backward: Optional[Callable] = None,
                       num_outputs: int = 1):
    """Register ``forward`` (a jax function, typically wrapping
    ``pl.pallas_call``) as custom op ``name``.

    forward(*arrays) -> array | tuple: the device computation.
    backward(residuals, *cotangents) -> input cotangents (optional): when
    given, a ``jax.custom_vjp`` wraps the forward — residuals are
    ``(inputs, outputs)`` — so the Pallas backward kernel provides the
    gradient (the flash-attention pattern,
    pallas_kernels/flash_attention.py). Without it the op is
    NON-differentiable (Pallas kernels are opaque to autodiff), exactly
    like the reference, where a custom op without a registered grad op
    cannot be trained through.

    Returns the op callable (also registered for ``get_custom_op``).
    """
    if backward is not None:
        @jax.custom_vjp
        def kernel(*arrays):
            return forward(*arrays)

        def fwd(*arrays):
            out = forward(*arrays)
            return out, (arrays, out)

        def bwd(res, cots):
            arrays, out = res
            grads = backward(res, *(cots if isinstance(cots, tuple) else (cots,)))
            return tuple(grads)

        kernel.defvjp(fwd, bwd)
    else:
        kernel = forward

    def op(*tensors):
        ts = [ensure_tensor(t) for t in tensors]
        if backward is None:
            # opaque device kernel: no tape entry (non-differentiable)
            from ..core.autograd import no_grad

            with no_grad():
                return apply_op(name, kernel, *ts)
        return apply_op(name, kernel, *ts)

    op.__name__ = name
    register_op(name, kind="pallas_custom", num_outputs=num_outputs,
                has_custom_backward=backward is not None)
    _CUSTOM_OPS[name] = op
    return op


def get_custom_op(name: str) -> Callable:
    return _CUSTOM_OPS[name]
