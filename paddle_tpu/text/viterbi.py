"""Viterbi decoding for linear-chain CRF outputs.

Parity: python/paddle/text/viterbi_decode.py (ViterbiDecoder,
viterbi_decode — kernel phi/kernels/cpu/viterbi_decode_kernel.cc).

TPU design: the max-sum recursion is a lax.scan over time with batched
[B, N, N] score broadcasting — one fused compiled loop instead of the
reference's per-step kernel; backtracking is a second scan over the
argmax history.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer import Layer
from ..ops.dispatch import apply_op

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def _viterbi(potentials, trans, lengths, include_bos_eos_tag: bool):
    # potentials: [B, T, N]; trans: [N, N]; lengths: [B]
    B, T, N = potentials.shape
    if include_bos_eos_tag:
        # reference convention: tag N-2 = BOS, N-1 = EOS
        alpha0 = potentials[:, 0] + trans[N - 2][None, :]
    else:
        alpha0 = potentials[:, 0]

    def step(carry, t):
        alpha, _ = carry
        emit = potentials[:, t]                     # [B, N]
        scores = alpha[:, :, None] + trans[None]    # [B, N_from, N_to]
        best_prev = jnp.argmax(scores, axis=1)      # [B, N]
        best_score = jnp.max(scores, axis=1) + emit
        # positions beyond the sequence keep their alpha (masked update)
        live = (t < lengths)[:, None]
        new_alpha = jnp.where(live, best_score, alpha)
        return (new_alpha, None), best_prev

    (alpha, _), history = jax.lax.scan(step, (alpha0, None), jnp.arange(1, T))
    # history: [T-1, B, N]
    if include_bos_eos_tag:
        alpha = alpha + trans[:, N - 1][None, :]

    scores = jnp.max(alpha, axis=-1)
    last_tag = jnp.argmax(alpha, axis=-1)           # [B]

    def backstep(tag, t):
        prev = history[t]                           # [B, N]
        new_tag = jnp.take_along_axis(prev, tag[:, None], axis=1)[:, 0]
        live = (t + 1) < lengths
        new_tag = jnp.where(live, new_tag, tag)
        return new_tag, tag

    first_tag, path_rev = jax.lax.scan(backstep, last_tag, jnp.arange(T - 2, -1, -1))
    # scan outputs are the pre-update tags: [path[T-1], ..., path[1]]; the
    # final carry is path[0]
    path = jnp.concatenate([first_tag[None], path_rev[::-1]], axis=0)  # [T, B]
    return scores, path.T.astype(jnp.int64)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag: bool = True, name=None):
    """Returns (scores [B], paths [B, T]) of the best tag sequences."""
    lens = lengths._data if isinstance(lengths, Tensor) else jnp.asarray(lengths)

    def fn(pot, trans):
        return _viterbi(pot, trans, lens, include_bos_eos_tag)

    return apply_op("viterbi_decode", fn, potentials, transition_params)


class ViterbiDecoder(Layer):
    def __init__(self, transitions, include_bos_eos_tag: bool = True, name=None):
        super().__init__()
        self.transitions = transitions if isinstance(transitions, Tensor) else Tensor(transitions)
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
