"""paddle.text equivalent — text datasets + sequence decoding.

Parity: python/paddle/text/ (datasets/{imdb,imikolov,uci_housing,...}.py,
viterbi_decode.py). Zero-egress environment: dataset classes parse local
files in the reference formats via ``data_file=`` instead of downloading.
"""

from .datasets import Imdb, Imikolov, UCIHousing
from .viterbi import ViterbiDecoder, viterbi_decode

__all__ = ["Imdb", "Imikolov", "UCIHousing", "ViterbiDecoder", "viterbi_decode"]
