"""Text datasets (parity: python/paddle/text/datasets/ — Imdb, Imikolov,
UCIHousing). Zero-egress: each class reads a local ``data_file`` in the
reference's on-disk format instead of downloading."""

from __future__ import annotations

import os
import re
import tarfile
from typing import List, Optional

import numpy as np

from ..io.dataset import Dataset


class UCIHousing(Dataset):
    """Boston-housing regression table: whitespace-separated rows of 14
    floats (13 features + target), normalized like the reference
    (uci_housing.py feature scaling)."""

    def __init__(self, data_file: str, mode: str = "train"):
        if not os.path.exists(data_file):
            raise FileNotFoundError(
                f"UCIHousing needs a local copy of the housing data at {data_file} "
                "(no network access; place the UCI housing.data file there)")
        raw = np.loadtxt(data_file, dtype=np.float32)
        if raw.ndim == 1:
            raw = raw.reshape(-1, 14)
        feats, target = raw[:, :13], raw[:, 13:]
        mins, maxs = feats.min(0), feats.max(0)
        span = np.where(maxs > mins, maxs - mins, 1.0)
        feats = (feats - feats.mean(0)) / span
        split = int(len(feats) * 0.8)
        if mode == "train":
            self.data = np.concatenate([feats[:split], target[:split]], axis=1)
        else:
            self.data = np.concatenate([feats[split:], target[split:]], axis=1)

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:13], row[13:]

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """IMDB sentiment dataset from the reference's aclImdb tarball layout
    (imdb.py: tar members aclImdb/{train,test}/{pos,neg}/*.txt)."""

    def __init__(self, data_file: str, mode: str = "train", cutoff: int = 150):
        if not os.path.exists(data_file):
            raise FileNotFoundError(
                f"Imdb needs the aclImdb tarball at {data_file} (no network access)")
        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        docs: List[List[str]] = []
        labels: List[int] = []
        freq: dict = {}
        with tarfile.open(data_file) as tf:
            for member in tf.getmembers():
                m = pat.match(member.name)
                if not m:
                    continue
                text = tf.extractfile(member).read().decode("utf-8", "ignore").lower()
                words = re.sub(r"[^a-z0-9\s]", "", text).split()
                docs.append(words)
                labels.append(0 if m.group(1) == "neg" else 1)
                for w in words:
                    freq[w] = freq.get(w, 0) + 1
        kept = [w for w, c in sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
                if c >= cutoff]
        self.word_idx = {w: i for i, w in enumerate(kept)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.array([self.word_idx.get(w, unk) for w in d], np.int64)
                     for d in docs]
        self.labels = np.array(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB-style n-gram dataset (imikolov.py): one sentence per line; yields
    n-gram windows over <s> ... </e> wrapped sentences."""

    def __init__(self, data_file: str, data_type: str = "NGRAM", window_size: int = 5,
                 mode: str = "train", min_word_freq: int = 50):
        if not os.path.exists(data_file):
            raise FileNotFoundError(
                f"Imikolov needs a local corpus file at {data_file} (no network access)")
        with open(data_file, encoding="utf-8") as f:
            lines = [l.strip().split() for l in f if l.strip()]
        freq: dict = {}
        for words in lines:
            for w in words:
                freq[w] = freq.get(w, 0) + 1
        kept = [w for w, c in sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
                if c >= min_word_freq]
        self.word_idx = {w: i for i, w in enumerate(kept)}
        for tok in ("<s>", "<e>", "<unk>"):
            if tok not in self.word_idx:
                self.word_idx[tok] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.data = []
        for words in lines:
            ids = ([self.word_idx["<s>"]]
                   + [self.word_idx.get(w, unk) for w in words]
                   + [self.word_idx["<e>"]])
            if data_type.upper() == "NGRAM":
                for i in range(len(ids) - window_size + 1):
                    self.data.append(np.array(ids[i:i + window_size], np.int64))
            else:  # SEQ
                self.data.append((np.array(ids[:-1], np.int64), np.array(ids[1:], np.int64)))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)
