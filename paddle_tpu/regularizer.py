"""paddle.regularizer equivalent — L1Decay / L2Decay.

Parity: python/paddle/regularizer.py. The optimizer base consumes the
``_coeff`` attribute for coupled decay (optimizer.py _apply_decay); L1
applies through the same hook as a sign-gradient penalty.
"""

from __future__ import annotations

__all__ = ["L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    def __init__(self, coeff: float = 0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self) -> float:
        return self._coeff

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self._coeff})"


class L1Decay(WeightDecayRegularizer):
    """loss += coeff * sum(|w|); gradient contribution coeff * sign(w)."""

    def grad_term(self, param_data):
        import jax.numpy as jnp

        return self._coeff * jnp.sign(param_data)


class L2Decay(WeightDecayRegularizer):
    """loss += 0.5 * coeff * sum(w^2); gradient contribution coeff * w."""

    def grad_term(self, param_data):
        return self._coeff * param_data
