"""Dynamic-to-static entry points.

Parity: python/paddle/jit/api.py:195 ``to_static``. TPU design: the eager op
layer is already jax-traceable (every op is a pure jax function on the
Tensor payload), so ``to_static`` wraps the python function so its Tensor
inputs carry tracers, and jits the whole thing — the analogue of the
reference's SOT trace → whole-program PIR → compiled executable, with XLA
as the compiler instead of CINN. Guards/cache are keyed by input spec
(shape, dtype) exactly like ``ConcreteProgram`` caching.
"""

from __future__ import annotations

import functools
import threading
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ..core.autograd import no_grad
from ..core.tensor import Tensor
from ..observability.recompile import entrypoint as _entrypoint
from ..observability.recompile import \
    register_entry_location as _register_entry

_tls = threading.local()


def in_to_static_mode() -> bool:
    return getattr(_tls, "tracing", 0) > 0


class _TraceScope:
    def __enter__(self):
        _tls.tracing = getattr(_tls, "tracing", 0) + 1

    def __exit__(self, *exc):
        _tls.tracing -= 1
        return False


def _wrap_in(x):
    if isinstance(x, (jax.Array, jax.core.Tracer)):
        return Tensor(x, stop_gradient=True)
    return x


def _unwrap_out(x):
    if isinstance(x, Tensor):
        return x._data
    return x


class StaticFunction:
    """Compiled-function wrapper (parity: program_translator.py
    SymbolicStaticFunction). Cache key = jax.jit's trace cache (shapes,
    dtypes, static args)."""

    def __init__(self, fn: Callable, build_strategy=None, backend=None, donate_argnums=()):
        self._fn = fn
        self._sot = None  # set on first graph break (SOT-lite fallback)
        # recompile-monitor attribution: compiles triggered while this
        # entry dispatches are charged to it; a compile AFTER the first
        # completed call is flagged as a retrace (shape/dtype churn)
        self._entry_name = "to_static:" + getattr(
            fn, "__qualname__", getattr(fn, "__name__", "fn"))
        # retrace warnings cite the wrapped function's file:line (the
        # spot the static analyzer's findings also point at)
        _register_entry(self._entry_name, fn)
        functools.update_wrapper(self, fn, updated=[])

        # compiled control flow (reference: dy2static AST transformers):
        # simple tensor-valued while/if lower to lax.while_loop/lax.cond so
        # ONE program covers all iteration counts; SOT-lite stays the
        # fallback for whatever the pass declines
        traced_fn = fn
        try:
            from .ast_transform import transform_control_flow

            transformed = transform_control_flow(fn)
        except Exception:
            transformed = None
        if transformed is not None:
            traced_fn = transformed
        self.uses_compiled_control_flow = transformed is not None
        self._donate_argnums = donate_argnums
        self._jitted = self._build_jitted(traced_fn)

    def _build_jitted(self, traced_fn):
        def runner(*datas, **kw):
            with _TraceScope(), no_grad():
                args = jax.tree.map(_wrap_in, datas, is_leaf=lambda x: isinstance(x, (jax.Array, jax.core.Tracer)))
                kwargs = jax.tree.map(_wrap_in, kw, is_leaf=lambda x: isinstance(x, (jax.Array, jax.core.Tracer)))
                out = traced_fn(*args, **kwargs)
                return jax.tree.map(_unwrap_out, out, is_leaf=lambda x: isinstance(x, Tensor))

        return jax.jit(runner, donate_argnums=self._donate_argnums)

    def __call__(self, *args, **kwargs):
        with _entrypoint(self._entry_name):
            return self._call_impl(*args, **kwargs)

    def _call_impl(self, *args, **kwargs):
        datas = jax.tree.map(lambda x: x._data if isinstance(x, Tensor) else x, args,
                             is_leaf=lambda x: isinstance(x, Tensor))
        kw = jax.tree.map(lambda x: x._data if isinstance(x, Tensor) else x, kwargs,
                          is_leaf=lambda x: isinstance(x, Tensor))
        if self._sot is None:
            try:
                out = self._jitted(*datas, **kw)
                return jax.tree.map(lambda x: Tensor(x) if isinstance(x, jax.Array) else x, out)
            except (jax.errors.ConcretizationTypeError,
                    jax.errors.TracerBoolConversionError,
                    jax.errors.TracerIntegerConversionError,
                    jax.errors.TracerArrayConversionError):
                # GRAPH BREAK: data-dependent Python control flow. Fall back
                # to SOT-lite guarded path programs (reference: SOT
                # eval-frame fallback, opcode_executor.py graph breaks).
                from .sot_lite import SotFunction

                self._sot = SotFunction(self._fn, _wrap_in, _unwrap_out)
                self.uses_compiled_control_flow = False  # SOT serves calls
            except Exception as e:
                from ..observability import perf as _perf

                if _perf.is_oom_error(e):
                    # device allocation failure: write the OOM forensics
                    # dump (HBM ledger + top temp-byte executables) so
                    # the failure names its culprit, then propagate —
                    # an OOM is never a graph break to retry around
                    _perf.dump_oom(e)
                    raise
                if not self.uses_compiled_control_flow:
                    raise
                # the control-flow rewrite produced something lax cannot
                # express (shape-changing carry, non-array state): retry on
                # the ORIGINAL function, whose own failure modes route to
                # SOT-lite as before
                self.uses_compiled_control_flow = False
                self._jitted = self._build_jitted(self._fn)
                return self._call_impl(*args, **kwargs)
        out = self._sot(*datas, **kw)
        return jax.tree.map(lambda x: Tensor(x) if isinstance(x, jax.Array) else x, out)

    @property
    def sot_graph_count(self):
        """Compiled sub-graph count after graph breaks (None = no break)."""
        return None if self._sot is None else self._sot.graph_count

    @property
    def code(self):
        return self._fn.__code__

    def concrete_program(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)


def to_static(function=None, input_spec=None, build_strategy=None, backend=None, **kwargs):
    """Decorator converting a dygraph function/Layer to a compiled program."""

    def decorate(fn):
        from ..nn.layer import Layer

        if isinstance(fn, Layer):
            return _LayerStaticWrapper(fn)
        return StaticFunction(fn, build_strategy, backend)

    if function is not None:
        return decorate(function)
    return decorate


class _LayerStaticWrapper:
    """to_static over an nn.Layer: parameters become jit inputs so updates
    don't retrigger compilation."""

    def __init__(self, layer):
        self._layer = layer
        self._entry_name = "to_static:" + type(layer).__name__
        _register_entry(self._entry_name, type(layer))

        def runner(params, buffers, *datas, **kw):
            with _TraceScope(), no_grad():
                from ..utils.functional import functional_call

                out = functional_call(layer, {**params, **buffers}, *[_wrap_in(d) for d in datas],
                                      **{k: _wrap_in(v) for k, v in kw.items()})
                return jax.tree.map(_unwrap_out, out, is_leaf=lambda x: isinstance(x, Tensor))

        self._jitted = jax.jit(runner)

    def __getattr__(self, name):
        return getattr(self._layer, name)

    def __call__(self, *args, **kwargs):
        params = {k: v._data for k, v in self._layer.named_parameters_dict().items()}
        buffers = {k: v._data for k, v in self._layer.named_buffers_dict().items()}
        datas = [a._data if isinstance(a, Tensor) else a for a in args]
        kw = {k: (v._data if isinstance(v, Tensor) else v) for k, v in kwargs.items()}
        with _entrypoint(self._entry_name):
            out = self._jitted(params, buffers, *datas, **kw)
        return jax.tree.map(lambda x: Tensor(x) if isinstance(x, jax.Array) else x, out)


def not_to_static(fn=None):
    if fn is None:
        return lambda f: f
    return fn


def ignore_module(modules):
    return None
