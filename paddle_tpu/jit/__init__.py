from .api import StaticFunction, ignore_module, in_to_static_mode, not_to_static, to_static
from .save_load import TranslatedLayer, load, save

__all__ = ["to_static", "not_to_static", "in_to_static_mode", "StaticFunction",
           "ignore_module", "save", "load", "TranslatedLayer"]
