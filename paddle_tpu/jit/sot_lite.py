"""SOT-lite: graph-break fallback for ``to_static``.

Parity: the reference's SOT (python/paddle/jit/sot/opcode_translator/
executor/opcode_executor.py, eval_frame_callback.py:54) traces bytecode,
emits guards over frame state, and falls back to eager at graph breaks.

TPU-native design — guard-specialized path programs instead of bytecode
simulation:

1. A plain ``jax.jit`` trace is tried first (the fast path). If the
   function concretizes a traced Tensor (``if tensor:``, ``int(t)``,
   ``t.item()``) jax raises a concretization error = a GRAPH BREAK.
2. On break, the call runs EAGERLY (the fallback), recording the concrete
   outcome of every concretization — the path signature.
3. The function is then re-traced with those outcomes REPLAYED at each
   break, producing one compiled program per control-flow path. Each path
   program also outputs the condition values it observed — its guards,
   compiled into the program exactly like SOT's guard expressions.
4. Dispatch: run the most-recently-used matching path; compare its
   reported conditions with the path's signature. A mismatch reveals the
   true outcome prefix (conditions are trustworthy up to and including
   the first divergence), which selects/creates the right path program.

Cache shape: {input aval spec -> {outcomes tuple -> jitted program}};
discovery is one eager run per new path (the reference pays the same: a
break triggers eager execution of the rest of the frame). The SPEC level
is the shape guard: a path recorded under one set of input shapes/dtypes
is never dispatched for another, mirroring the reference SOT's frame
guards over tensor metadata.

GUARD TOLERANCE CONTRACT: bool/int guards compare exactly; float guards
compare to 1e-5 relative (1e-6 absolute at zero) because a fused
program's float may lawfully differ from the eager probe in the last
ulps. Two paths whose float outcomes differ by LESS than that tolerance
are the same path by contract — code whose control flow flips on <1e-5
relative float differences is outside SOT-lite's guarantee (use
compiled control flow via jit/ast_transform.py, or int/bool guards).
"""

from __future__ import annotations

import functools
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core import tensor as tensor_mod
from ..core.autograd import no_grad
from ..core.tensor import Tensor

_tls = threading.local()


class _Ctx:
    __slots__ = ("mode", "outcomes", "idx", "cond_tracers")

    def __init__(self, mode: str, outcomes: Optional[List[Any]] = None):
        self.mode = mode                      # "probe" | "replay"
        self.outcomes = outcomes if outcomes is not None else []
        self.idx = 0
        self.cond_tracers: List[Any] = []


def _hook(data):
    """Concretization interception (installed as Tensor._concretize_hook).
    Returns (handled, value)."""
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        return False, None
    if ctx.mode == "probe":
        v = data.item()                        # concrete during eager probe
        ctx.outcomes.append(v)
        return True, v
    # replay (inside a jit trace): the traced condition becomes a guard
    # output; the recorded outcome steers Python control flow
    ctx.cond_tracers.append(jnp.asarray(data))
    if ctx.idx >= len(ctx.outcomes):
        raise RuntimeError(
            "to_static graph-break replay diverged: more concretization "
            "points than the recorded path (non-deterministic branching?)")
    v = ctx.outcomes[ctx.idx]
    ctx.idx += 1
    return True, v


def _install_hook():
    tensor_mod._concretize_hook[0] = _hook


class _PushCtx:
    def __init__(self, ctx):
        self.ctx = ctx

    def __enter__(self):
        self.prev = getattr(_tls, "ctx", None)
        _tls.ctx = self.ctx
        return self.ctx

    def __exit__(self, *exc):
        _tls.ctx = self.prev
        return False


def _match_outcome(reported, recorded) -> bool:
    """Guard comparison: exact for bools/ints, approximate for floats (a
    fused program's float may differ from the eager probe in the last
    ulp). See the module docstring's tolerance contract."""
    if isinstance(recorded, bool):
        return bool(reported) == recorded
    if isinstance(recorded, int):
        return int(reported) == recorded
    rf, cf = float(reported), float(recorded)
    if cf == 0.0:
        return abs(rf) < 1e-6
    return abs(rf - cf) <= 1e-5 * abs(cf)


MAX_PATHS = 64    # value-specialized paths cap PER INPUT SPEC; a spec
                  # that overflows degrades to eager for that spec only
MAX_SPECS = 256   # total spec tables kept; oldest evicted beyond this


class SotFunction:
    """Path-specialized compilation with compiled guards (SOT-lite)."""

    def __init__(self, fn: Callable, wrap_in, unwrap_out):
        self._fn = fn
        self._wrap_in = wrap_in
        self._unwrap_out = unwrap_out
        # spec -> {outcomes -> jitted program | None} (None = eager-only
        # path: its replay trace failed, e.g. an unhookable concretization
        # like np.asarray(tracer) — the reference SOT also stays eager
        # there). spec = input (shape, dtype) tuple — the shape guard.
        self._paths: Dict[Tuple, Dict[Tuple, Any]] = {}
        self._mru: Dict[Tuple, Tuple] = {}
        self._eager_specs: set = set()  # specs whose path cache overflowed
        _install_hook()

    # -- program construction ---------------------------------------------
    def _build_program(self, outcomes: Tuple):
        fn, wrap_in, unwrap_out = self._fn, self._wrap_in, self._unwrap_out

        def runner(*datas, **kw):
            ctx = _Ctx("replay", list(outcomes))
            from .api import _TraceScope

            with _PushCtx(ctx), _TraceScope(), no_grad():
                args = jax.tree.map(wrap_in, datas,
                                    is_leaf=lambda x: isinstance(x, (jax.Array, jax.core.Tracer)))
                kwargs = jax.tree.map(wrap_in, kw,
                                      is_leaf=lambda x: isinstance(x, (jax.Array, jax.core.Tracer)))
                out = fn(*args, **kwargs)
                out_datas = jax.tree.map(unwrap_out, out,
                                         is_leaf=lambda x: isinstance(x, Tensor))
            return out_datas, tuple(ctx.cond_tracers)

        return jax.jit(runner)

    @staticmethod
    def _spec(datas, kw) -> Tuple:
        """Input metadata guard: (shape, dtype) per array leaf."""
        return tuple((tuple(x.shape), str(x.dtype))
                     for x in jax.tree.leaves((datas, kw))
                     if isinstance(x, jax.Array))

    def _total_paths(self) -> int:
        return sum(len(d) for d in self._paths.values())

    # -- discovery: eager fallback + path compile -------------------------
    def _discover(self, datas, kw, spec=None):
        ctx = _Ctx("probe")
        with _PushCtx(ctx), no_grad():
            args = jax.tree.map(lambda x: Tensor(x, stop_gradient=True)
                                if isinstance(x, jax.Array) else x, datas,
                                is_leaf=lambda x: isinstance(x, jax.Array))
            kwargs = jax.tree.map(lambda x: Tensor(x, stop_gradient=True)
                                  if isinstance(x, jax.Array) else x, kw,
                                  is_leaf=lambda x: isinstance(x, jax.Array))
            out = self._fn(*args, **kwargs)
            out_datas = jax.tree.map(lambda x: x._data if isinstance(x, Tensor) else x,
                                     out, is_leaf=lambda x: isinstance(x, Tensor))
        key = tuple(ctx.outcomes)
        if spec is None:
            spec = self._spec(datas, kw)
        if spec in self._eager_specs:
            return out_datas  # no cache bookkeeping for degraded specs
        paths = self._paths.setdefault(spec, {})
        if key not in paths:
            if len(paths) >= MAX_PATHS:
                # value-varying concretizations (e.g. float(loss) logged
                # every step) would specialize forever: degrade THIS spec
                # to eager and free its programs; other specs keep theirs
                self._eager_specs.add(spec)
                self._paths.pop(spec, None)
                self._mru.pop(spec, None)
                return out_datas
            paths[key] = self._build_program(key)
            while len(self._paths) > MAX_SPECS:  # bound total spec tables
                oldest = next(iter(self._paths))
                self._paths.pop(oldest)
                self._mru.pop(oldest, None)
        self._mru[spec] = key
        return out_datas

    def _find_path(self, spec: Tuple, prefix: Tuple, tried) -> Optional[Tuple]:
        paths = self._paths.get(spec, {})

        def matches(key):
            return (key not in tried and len(key) >= len(prefix)
                    and all(_match_outcome(p, k) for p, k in zip(prefix, key)))

        mru = self._mru.get(spec)
        if mru is not None and mru in paths and matches(mru):
            return mru
        for key in paths:
            if matches(key):
                return key
        return None

    # -- dispatch ----------------------------------------------------------
    def __call__(self, *datas, **kw):
        spec = self._spec(datas, kw)
        if spec in self._eager_specs:
            return self._discover(datas, kw, spec)
        tried = set()
        prefix: Tuple = ()
        while True:
            key = self._find_path(spec, prefix, tried)
            if key is None:
                return self._discover(datas, kw, spec)
            program = self._paths[spec][key]
            if program is None:  # known eager-only path
                return self._discover(datas, kw, spec)
            try:
                out, conds = program(*datas, **kw)
            except (jax.errors.ConcretizationTypeError,
                    jax.errors.TracerBoolConversionError,
                    jax.errors.TracerIntegerConversionError,
                    jax.errors.TracerArrayConversionError,
                    RuntimeError):
                # retrace failed (unhookable concretization, or the
                # concretization count depends on input shape): this path
                # program can't serve these avals — run eagerly
                self._paths[spec][key] = None
                return self._discover(datas, kw, spec)
            conds_py = [jax.device_get(c) for c in conds]
            mismatch = None
            for i, (rep, rec) in enumerate(zip(conds_py, key)):
                if not _match_outcome(rep, rec):
                    mismatch = i
                    break
            if mismatch is None:
                self._mru[spec] = key
                return out
            tried.add(key)
            # conditions are valid up to and including the first divergence
            verified = list(key[:mismatch])
            rep = conds_py[mismatch]
            rec = key[mismatch]
            if isinstance(rec, bool):
                verified.append(bool(rep))
            elif isinstance(rec, int):
                verified.append(int(rep))
            else:
                verified.append(float(rep))
            prefix = tuple(verified)

    @property
    def graph_count(self) -> int:
        """Number of compiled sub-graphs (path programs, all input specs)."""
        return self._total_paths()
