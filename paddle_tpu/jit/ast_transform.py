"""Compiled control flow for dy2static: AST-transform simple ``while``/
``if`` statements into lax.while_loop / lax.cond.

Parity: python/paddle/jit/dy2static/transformers/loop_transformer.py and
ifelse_transformer.py — the reference rewrites tensor control flow into
IR while_op/cond_op so one static program covers all paths. Here the
rewrite targets XLA's structured control flow: a transformed loop
compiles to ONE program regardless of iteration count, instead of
SOT-lite's per-outcome path specialization (jit/sot_lite.py remains the
fallback for everything this pass cannot express).

Mechanics: ``while test: body`` becomes

    __pt_st = (v1, ..., vn)              # vars assigned in body
    def __pt_cond(s): v... = s; return test
    def __pt_body(s): v... = s; body; return (v...)
    __pt_st = __pt_while__(cond, body, __pt_st)
    (v1, ..., vn) = __pt_st

``__pt_while__`` dispatches at RUNTIME: a traced predicate runs
lax.while_loop; a concrete Python predicate runs the ordinary loop —
so the transform is semantics-preserving for plain-Python control flow.

A statement is transformed only when it is statically safe: no
break/continue/return inside, and every assigned variable is already
bound earlier in the function (so the state tuple is well-defined).
"""

from __future__ import annotations

import ast
import inspect
import textwrap
from typing import Callable, List, Optional, Set

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["transform_control_flow"]


# ---------------------------------------------------------------------------
# runtime helpers (injected as __pt_while__ / __pt_if__)
# ---------------------------------------------------------------------------

def _unwrap(v):
    return v._data if isinstance(v, Tensor) else v


def _is_traced(x):
    return isinstance(x, jax.core.Tracer)


def _state_codec(state: tuple):
    """(to_arr, to_state): strip Tensor wrappers for lax, restore them for
    user code — wrapper positions recorded once at entry."""
    flags = [isinstance(v, Tensor) for v in state]

    def to_arr(s):
        return tuple(_unwrap(v) for v in s)

    def to_state(arrs):
        return tuple(Tensor(a, stop_gradient=True) if f else a
                     for f, a in zip(flags, arrs))

    return to_arr, to_state


def _pt_while(cond_fn: Callable, body_fn: Callable, state: tuple) -> tuple:
    state = tuple(state)
    p0 = _unwrap(cond_fn(state))
    if not _is_traced(p0):
        # concrete predicate: ordinary Python loop (identical semantics)
        while bool(p0):
            state = tuple(body_fn(state))
            p0 = _unwrap(cond_fn(state))
        return state

    from jax import lax

    to_arr, to_state = _state_codec(state)

    def c(arrs):
        return jnp.asarray(_unwrap(cond_fn(to_state(arrs)))).reshape(())

    def b(arrs):
        return to_arr(tuple(body_fn(to_state(arrs))))

    out = lax.while_loop(c, b, to_arr(state))
    return to_state(out)


def _pt_if(pred, true_fn: Callable, false_fn: Callable, state: tuple) -> tuple:
    state = tuple(state)
    p = _unwrap(pred)
    if not _is_traced(p):
        return tuple(true_fn(state)) if bool(p) else tuple(false_fn(state))

    from jax import lax

    to_arr, to_state = _state_codec(state)

    def tf(arrs):
        return to_arr(tuple(true_fn(to_state(arrs))))

    def ff(arrs):
        return to_arr(tuple(false_fn(to_state(arrs))))

    out = lax.cond(jnp.asarray(p).reshape(()), tf, ff, to_arr(state))
    return to_state(out)


# ---------------------------------------------------------------------------
# the AST pass
# ---------------------------------------------------------------------------

def _assigned_names(stmts: List[ast.stmt]) -> Optional[Set[str]]:
    """Names bound by the statements; None when a construct we don't
    rewrite (nested defs, for-loops, with, try, del, star/attr targets)
    appears."""
    names: Set[str] = set()
    for st in stmts:
        for node in ast.walk(st):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.For, ast.AsyncFor,
                                 ast.With, ast.Try, ast.Delete,
                                 ast.Global, ast.Nonlocal)):
                return None
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                names.add(node.id)
            elif isinstance(node, (ast.Attribute, ast.Subscript)) and \
                    isinstance(node.ctx, ast.Store):
                return None  # mutation of containers: state unclear
    return names


def _has_jumps(stmts: List[ast.stmt]) -> bool:
    for st in stmts:
        for node in ast.walk(st):
            if isinstance(node, (ast.Break, ast.Continue, ast.Return)):
                return True
    return False


def _loaded_names(expr: ast.expr) -> Set[str]:
    return {n.id for n in ast.walk(expr)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _definitely_bound(st: ast.stmt) -> Set[str]:
    """Names bound on EVERY path through ``st`` — branch-only bindings must
    not count (state tuples read them unconditionally)."""
    if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = st.targets if isinstance(st, ast.Assign) else [st.target]
        out: Set[str] = set()
        for t in targets:
            for node in ast.walk(t):
                if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                    out.add(node.id)
        return out
    if isinstance(st, ast.If):
        t = set().union(*(_definitely_bound(s) for s in st.body)) \
            if st.body else set()
        f = set().union(*(_definitely_bound(s) for s in st.orelse)) \
            if st.orelse else set()
        return t & f if st.orelse else set()
    # loops may run zero times; with/try have exceptional paths — nothing
    # is definitely bound by them
    return set()


class _Rewriter:
    def __init__(self, func: ast.FunctionDef):
        self.func = func
        self.counter = 0
        self.applied = 0
        # names bound before a given lineno (params + prior assignments);
        # source-order approximation of definedness
        self.bound: Set[str] = {a.arg for a in func.args.args}
        self.bound |= {a.arg for a in func.args.kwonlyargs}
        if func.args.vararg:
            self.bound.add(func.args.vararg.arg)
        if func.args.kwarg:
            self.bound.add(func.args.kwarg.arg)

    def run(self):
        self.func.body = self._rewrite_block(self.func.body)
        return self.applied

    def _rewrite_block(self, stmts: List[ast.stmt]) -> List[ast.stmt]:
        out: List[ast.stmt] = []
        for st in stmts:
            replaced = None
            if isinstance(st, ast.While) and not st.orelse:
                replaced = self._try_while(st)
            elif isinstance(st, ast.If):
                replaced = self._try_if(st)
            if replaced is None:
                # recurse into compound bodies with a scoped bound set,
                # then record only this statement's DEFINITE bindings —
                # branch-only names would make a later generated state
                # tuple read unbound locals
                saved = set(self.bound)
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(st, attr, None)
                    if sub:
                        setattr(st, attr, self._rewrite_block(sub))
                self.bound = saved | _definitely_bound(st)
                out.append(st)
            else:
                out.extend(replaced)
        return out

    def _state_vars(self, body_names: Set[str], test: ast.expr) -> List[str]:
        vars_ = body_names | (_loaded_names(test) & self.bound)
        return sorted(vars_)

    def _split_temps(self, body: List[ast.stmt], body_names: Set[str],
                     after_lineno: int) -> Optional[Set[str]]:
        """Partition body-assigned names: names NOT bound before the block
        may stay block-local temps iff (a) assigned before first use inside
        the block and (b) never read after the block (zero-iteration reads
        would be NameErrors the transform may not introduce). Returns the
        state-var subset, or None when the block can't be transformed."""
        temps = body_names - self.bound
        if not temps:
            return body_names
        # (b): loaded later in the function (source order)
        for node in ast.walk(self.func):
            if (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)
                    and node.id in temps
                    and getattr(node, "lineno", 0) > after_lineno):
                return None
        # (a): within the block, stores must precede loads per temp
        stored: Set[str] = set()
        for st in body:
            for node in ast.walk(st):
                if isinstance(node, ast.Name) and node.id in temps:
                    if isinstance(node.ctx, ast.Load) and node.id not in stored:
                        return None
                    if isinstance(node.ctx, ast.Store):
                        stored.add(node.id)
        return body_names - temps

    def _try_while(self, node: ast.While) -> Optional[List[ast.stmt]]:
        if _has_jumps(node.body):
            return None
        body_names = _assigned_names(node.body)
        if body_names is None or not body_names:
            return None
        body_names = self._split_temps(node.body, body_names,
                                       getattr(node, "end_lineno", 10**9))
        if body_names is None or not body_names:
            return None
        vars_ = self._state_vars(body_names, node.test)
        i = self.counter
        self.counter += 1
        tup = ", ".join(vars_) + ("," if len(vars_) == 1 else "")
        src = textwrap.dedent(f"""
            __pt_st_{i} = ({tup})
            def __pt_cond_{i}(__pt_s_{i}):
                ({tup}) = __pt_s_{i}
                return __PT_TEST__
            def __pt_body_{i}(__pt_s_{i}):
                ({tup}) = __pt_s_{i}
                __PT_BODY__
                return ({tup})
            __pt_st_{i} = __pt_while__(__pt_cond_{i}, __pt_body_{i}, __pt_st_{i})
            ({tup}) = __pt_st_{i}
        """)
        block = ast.parse(src).body
        cond_def, body_def = block[1], block[2]
        cond_def.body[1] = ast.Return(value=node.test)
        body_def.body[1:2] = node.body  # replace __PT_BODY__ placeholder
        self.applied += 1
        return [ast.fix_missing_locations(ast.copy_location(s, node))
                for s in block]

    def _try_if(self, node: ast.If) -> Optional[List[ast.stmt]]:
        if _has_jumps(node.body) or _has_jumps(node.orelse):
            return None
        tnames = _assigned_names(node.body)
        fnames = _assigned_names(node.orelse) if node.orelse else set()
        if tnames is None or fnames is None:
            return None
        end = getattr(node, "end_lineno", 10**9)
        tnames = self._split_temps(node.body, tnames, end)
        fnames = self._split_temps(node.orelse, fnames, end) \
            if node.orelse else fnames
        if tnames is None or fnames is None:
            return None
        body_names = tnames | fnames
        if not body_names:
            return None
        vars_ = self._state_vars(body_names, node.test)
        i = self.counter
        self.counter += 1
        tup = ", ".join(vars_) + ("," if len(vars_) == 1 else "")
        src = textwrap.dedent(f"""
            __pt_st_{i} = ({tup})
            def __pt_true_{i}(__pt_s_{i}):
                ({tup}) = __pt_s_{i}
                __PT_BODY__
                return ({tup})
            def __pt_false_{i}(__pt_s_{i}):
                ({tup}) = __pt_s_{i}
                __PT_ELSE__
                return ({tup})
            __pt_st_{i} = __pt_if__(__PT_TEST__, __pt_true_{i}, __pt_false_{i}, __pt_st_{i})
            ({tup}) = __pt_st_{i}
        """)
        block = ast.parse(src).body
        true_def, false_def, call_stmt = block[1], block[2], block[3]
        true_def.body[1:2] = node.body
        if node.orelse:
            false_def.body[1:2] = node.orelse
        else:
            del false_def.body[1]
        call_stmt.value.args[0] = node.test
        self.applied += 1
        return [ast.fix_missing_locations(ast.copy_location(s, node))
                for s in block]


def transform_control_flow(fn: Callable) -> Optional[Callable]:
    """Return a variant of ``fn`` whose simple while/if statements route
    through __pt_while__/__pt_if__, or None when nothing applies (no
    source, closures, or no eligible statement)."""
    if getattr(fn, "__closure__", None):
        return None  # freevars would be lost on re-exec
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return None
    if not tree.body or not isinstance(tree.body[0], ast.FunctionDef):
        return None
    func = tree.body[0]
    func.decorator_list = []
    if _rewrite(func) == 0:
        return None
    ast.fix_missing_locations(tree)
    code = compile(tree, filename=f"<dy2static {fn.__name__}>", mode="exec")
    # live-globals proxy: helpers resolve locally, everything else falls
    # through to fn's REAL module globals — forward references defined
    # after decoration and test monkeypatching keep working
    glb = _GlobalsProxy(fn.__globals__,
                        {"__pt_while__": _pt_while, "__pt_if__": _pt_if})
    loc: dict = {}
    exec(code, glb, loc)
    new_fn = loc[func.name]
    new_fn.__defaults__ = fn.__defaults__
    new_fn.__kwdefaults__ = fn.__kwdefaults__
    return new_fn


class _GlobalsProxy(dict):
    """exec globals that overlay helper names on a LIVE base dict
    (CPython consults __missing__ for dict-subclass globals)."""

    def __init__(self, base: dict, extra: dict):
        super().__init__(extra)
        self._base = base

    def __missing__(self, key):
        return self._base[key]


def _rewrite(func: ast.FunctionDef) -> int:
    return _Rewriter(func).run()
