"""Compiled control flow for dy2static: AST-transform ``while``/``if``/
``for range`` statements (with break/continue) into lax control flow.

Parity: python/paddle/jit/dy2static/transformers/loop_transformer.py and
ifelse_transformer.py — the reference rewrites tensor control flow into
IR while_op/cond_op so one static program covers all paths. Here the
rewrite targets XLA's structured control flow: a transformed loop
compiles to ONE program regardless of iteration count, instead of
SOT-lite's per-outcome path specialization (jit/sot_lite.py remains the
fallback for everything this pass cannot express).

Mechanics: ``while test: body`` becomes

    __pt_st = (v1, ..., vn)              # vars assigned in body
    def __pt_cond(s): v... = s; return test
    def __pt_body(s): v... = s; body; return (v...)
    __pt_st = __pt_while__(cond, body, __pt_st)
    (v1, ..., vn) = __pt_st

``__pt_while__`` dispatches at RUNTIME: a traced predicate runs
lax.while_loop; a concrete Python predicate runs the ordinary loop —
so the transform is semantics-preserving for plain-Python control flow.

``for v in range(...)`` desugars to an index while (loop_transformer.py
:111 converts gast.For the same way); ``break``/``continue`` lower to
boolean state gating the rest of the body and the loop condition
(reference break_continue_transformer). Inner blocks are rewritten
before outer ones, so nested tensor loops compose into nested lax
control flow. A statement is transformed only when it is statically
safe: no ``return`` inside, and every state variable is already bound
earlier in the function (so the state tuple is well-defined).
"""

from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Callable, List, Optional, Set

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["transform_control_flow"]


# ---------------------------------------------------------------------------
# runtime helpers (injected as __pt_while__ / __pt_if__)
# ---------------------------------------------------------------------------

def _unwrap(v):
    return v._data if isinstance(v, Tensor) else v


def _is_traced(x):
    return isinstance(x, jax.core.Tracer)


def _state_codec(state: tuple):
    """(to_arr, to_state): strip Tensor wrappers for lax, restore them for
    user code — wrapper positions recorded once at entry."""
    flags = [isinstance(v, Tensor) for v in state]

    def to_arr(s):
        return tuple(_unwrap(v) for v in s)

    def to_state(arrs):
        return tuple(Tensor(a, stop_gradient=True) if f else a
                     for f, a in zip(flags, arrs))

    return to_arr, to_state


def _pt_while(cond_fn: Callable, body_fn: Callable, state: tuple) -> tuple:
    state = tuple(state)
    p0 = _unwrap(cond_fn(state))
    if not _is_traced(p0):
        # concrete predicate: ordinary Python loop (identical semantics).
        # The predicate can BECOME traced mid-loop — e.g. a lowered break
        # flag is concrete False on entry and a tracer after the first
        # body (its branch ran under lax.cond); switch to the lax loop
        # from the CURRENT state (completed iterations stay applied, the
        # failed bool() was only the next predicate check).
        try:
            while bool(p0):
                state = tuple(body_fn(state))
                p0 = _unwrap(cond_fn(state))
            return state
        except jax.errors.TracerBoolConversionError:
            pass

    from jax import lax

    to_arr, to_state = _state_codec(state)

    def c(arrs):
        return jnp.asarray(_unwrap(cond_fn(to_state(arrs)))).reshape(())

    def b(arrs):
        return to_arr(tuple(body_fn(to_state(arrs))))

    out = lax.while_loop(c, b, to_arr(state))
    return to_state(out)


def _pt_and_not(flag, test_val):
    """``(not flag) and test`` without Python short-circuit bool() —
    traced flags lower to logical ops (loop conditions after break
    lowering)."""
    b, t = _unwrap(flag), _unwrap(test_val)
    if _is_traced(b) or _is_traced(t):
        return jnp.logical_and(jnp.logical_not(b), t)
    return (not bool(b)) and bool(t)


def _pt_not_any(*flags):
    """``not (f1 or f2 ...)`` traced-safe (jump-guard predicates)."""
    vals = [_unwrap(f) for f in flags]
    if any(_is_traced(v) for v in vals):
        out = jnp.logical_not(vals[0])
        for v in vals[1:]:
            out = jnp.logical_and(out, jnp.logical_not(v))
        return out
    return not any(bool(v) for v in vals)


def _pt_range_cont(i, stop, step):
    """Continuation predicate of a desugared ``for ... in range``:
    direction-aware so negative literal/traced steps work."""
    iv, sv, st = _unwrap(i), _unwrap(stop), _unwrap(step)
    if _is_traced(iv) or _is_traced(sv) or _is_traced(st):
        return jnp.where(st > 0, iv < sv, iv > sv)
    if st == 0:  # match Python range() semantics, don't spin
        raise ValueError("range() arg 3 must not be zero")
    return iv < sv if st > 0 else iv > sv


class _PTUndefined:
    """Placeholder bound to a loop target when the sequence is empty —
    the python loop would leave the name unbound; any use raises the
    same UnboundLocalError plain python would (the reference dy2static's
    UndefinedVar role)."""

    def _raise(self, *a, **k):
        raise UnboundLocalError(
            "loop variable used but never bound: the iterated sequence "
            "was empty")

    __bool__ = __eq__ = __ne__ = __lt__ = __le__ = __gt__ = __ge__ = _raise
    __float__ = __int__ = __len__ = __iter__ = __array__ = _raise
    __add__ = __radd__ = __mul__ = __rmul__ = __getitem__ = __call__ = _raise

    def __repr__(self):
        return "<undefined loop variable (sequence was empty)>"


def _pt_seq_norm(seq):
    """Normalize an iterable to positional indexing BEFORE the index
    desugar (round 5; reference loop transformer covers dict iteration,
    dy2static/transformers/loop_transformer.py:111):

    - dicts and their views iterate in insertion order, so ``list(...)``
      reproduces python's semantics exactly (``for k in d`` yields keys;
      .values()/.items() likewise);
    - a uniform same-shape Tensor list stacks into a Tensor — rows then
      read through dynamic_index_in_dim, so a TRACED loop index (a
      tensor break/continue mid-loop) stays compilable where a python
      list would need int(tracer).

    Numeric lists/tuples stay python sequences (ADVICE round-5 fix):
    eagerly stacking them into a traced array turned every loop element
    into a tracer, so a body using the element as a python int
    (``range(n)``, list indexing, shape arithmetic) failed its trace
    and dragged the WHOLE function onto the retry/fallback path. On the
    positional-indexing path the elements stay python scalars; a loop
    that develops a TRACED index (tensor break/continue switching to
    lax) still reads numeric elements — _pt_seq_item lifts the sequence
    to an array lazily at that point, scoping the cost to the loops
    that need it.

    Sets stay undesugared (arbitrary iteration order is not worth
    freezing into a program) — _pt_seq_len declines them."""
    if isinstance(seq, dict):
        seq = list(seq.keys())
    elif isinstance(seq, (type({}.keys()), type({}.values()),
                          type({}.items()))):
        seq = list(seq)
    if isinstance(seq, (list, tuple)) and seq:
        if (all(isinstance(e, Tensor) for e in seq)
                and len({(tuple(e.shape), str(e.dtype)) for e in seq}) == 1):
            return Tensor(jnp.stack([e._data for e in seq]),
                          stop_gradient=True)
    return seq


def _pt_seq_len(seq):
    """Static iteration count of a ``for x in seq`` iterable: leading-dim
    size for tensors/arrays (a python int — shapes are static under
    trace), len() for positional sequences (dicts/views were normalized
    to key/value lists by _pt_seq_norm). Anything whose iteration order
    is not positional indexing (sets/generators) must NOT be desugared —
    raise so to_static falls back to the original function."""
    v = _unwrap(seq)
    shape = getattr(v, "shape", None)
    if shape is not None and getattr(v, "ndim", 1) >= 1:
        return int(shape[0])
    if not isinstance(seq, (list, tuple, str)):
        raise TypeError(
            f"for-seq transform supports tensors/arrays, list/tuple/str "
            f"and dict/dict-views, not {type(seq).__name__}")
    return len(seq)


def _pt_seq_fidx(seq):
    """Pre-bind for the enumerate index: 0 when the loop will run, the
    undefined sentinel for an empty sequence (plain python would leave
    the name unbound)."""
    return 0 if _pt_seq_len(seq) else _PTUndefined()


def _pt_seq_min_len(*seqs):
    """zip() iteration count: the shortest member."""
    return min(_pt_seq_len(s) for s in seqs)


def _pt_seq_first(seq, trip_count=None):
    """Pre-bind value for the loop target (lax carries need a concrete
    aval before the loop): element 0, or the undefined sentinel when the
    loop will not run (``trip_count`` — for zip this is the SHORTEST
    member's length, so a sibling's emptiness sentinels every target,
    matching python's leave-unbound)."""
    if (trip_count if trip_count is not None else _pt_seq_len(seq)) == 0:
        return _PTUndefined()
    v = _unwrap(seq)
    first = v[0] if getattr(v, "shape", None) is not None else seq[0]
    return Tensor(first, stop_gradient=True) if isinstance(seq, Tensor) else first


def _pt_seq_item(seq, i):
    """seq[i] with a possibly-traced index: dynamic_index_in_dim for
    tensors/arrays, plain indexing (concrete i) for python sequences.

    A python numeric sequence indexed by a TRACED i (a tensor
    break/continue switched the loop to lax mid-stream) lifts to an
    array at that point — the lazy form of the old eager numeric
    stacking, paid only by loops that actually develop a traced index;
    everyone else keeps python-int elements."""
    v = _unwrap(seq)
    if getattr(v, "shape", None) is not None and getattr(v, "ndim", None):
        out = jax.lax.dynamic_index_in_dim(v, jnp.asarray(i, jnp.int32), 0,
                                           keepdims=False)
        return Tensor(out, stop_gradient=True) if isinstance(seq, Tensor) else out
    if (_is_traced(_unwrap(i)) and isinstance(seq, (list, tuple)) and seq
            and all(isinstance(e, (int, float)) and not isinstance(e, bool)
                    for e in seq)):
        import numpy as _np

        return jax.lax.dynamic_index_in_dim(
            jnp.asarray(_np.asarray(seq)), jnp.asarray(i, jnp.int32), 0,
            keepdims=False)
    return seq[int(i)]


def _pt_if(pred, true_fn: Callable, false_fn: Callable, state: tuple) -> tuple:
    state = tuple(state)
    p = _unwrap(pred)
    if not _is_traced(p):
        return tuple(true_fn(state)) if bool(p) else tuple(false_fn(state))

    from jax import lax

    to_arr, to_state = _state_codec(state)

    def tf(arrs):
        return to_arr(tuple(true_fn(to_state(arrs))))

    def ff(arrs):
        return to_arr(tuple(false_fn(to_state(arrs))))

    out = lax.cond(jnp.asarray(p).reshape(()), tf, ff, to_arr(state))
    return to_state(out)


# ---------------------------------------------------------------------------
# the AST pass
# ---------------------------------------------------------------------------

def _iter_nodes(st: ast.stmt):
    """ast.walk, but generated ``__pt_*`` function defs (an already-
    transformed inner loop/if) are opaque: their bodies are
    self-contained state machines and must not contaminate the enclosing
    block's analysis."""
    yield st
    if isinstance(st, ast.FunctionDef) and st.name.startswith("__pt_"):
        return
    for child in ast.iter_child_nodes(st):
        yield from _iter_nodes(child)


def _assigned_names(stmts: List[ast.stmt]) -> Optional[Set[str]]:
    """Names bound by the statements; None when a construct we don't
    rewrite (nested defs, for-loops, with, try, del, star/attr targets)
    appears."""
    names: Set[str] = set()
    for st in stmts:
        for node in _iter_nodes(st):
            if isinstance(node, ast.FunctionDef) and \
                    node.name.startswith("__pt_"):
                names.add(node.name)
                continue
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.For, ast.AsyncFor,
                                 ast.With, ast.Try, ast.Delete,
                                 ast.Global, ast.Nonlocal)):
                return None
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                names.add(node.id)
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, ast.Store) and \
                    isinstance(node.value, ast.Name):
                # round 5: ``name[i] = v`` on a local — treat as binding
                # ``name``: Tensor __setitem__ rebinds the value
                # functionally (ops/__init__ _setitem -> _replace_), so
                # carrying the name through the loop/branch state machine
                # reproduces the mutation; python containers mutate in
                # place and ride the state tuple by identity. If the
                # state cannot be expressed as a lax carry, the generated
                # function fails at trace time and to_static retries the
                # original (api.py's graceful-decline path).
                names.add(node.value.id)
            elif isinstance(node, (ast.Attribute, ast.Subscript)) and \
                    isinstance(node.ctx, ast.Store):
                return None  # attribute / nested-container mutation
    return names


def _has_jumps(stmts: List[ast.stmt]) -> bool:
    for st in stmts:
        for node in ast.walk(st):
            if isinstance(node, (ast.Break, ast.Continue, ast.Return)):
                return True
    return False


def _has_returns(stmts: List[ast.stmt]) -> bool:
    return any(isinstance(n, ast.Return)
               for st in stmts for n in ast.walk(st))


def _assign_stmt(loc_node: ast.stmt, name: str, expr: ast.expr) -> ast.Assign:
    """``name = expr`` located at ``loc_node`` (shared by the for
    desugars)."""
    return ast.fix_missing_locations(ast.copy_location(ast.Assign(
        targets=[ast.Name(id=name, ctx=ast.Store())], value=expr),
        loc_node))


def _helper_call(fname: str, *argnames: str) -> ast.Call:
    """``__pt_helper__(name1, name2, ...)`` call expression."""
    return ast.Call(func=ast.Name(id=fname, ctx=ast.Load()),
                    args=[ast.Name(id=a, ctx=ast.Load())
                          for a in argnames], keywords=[])


def _assign_flag(name: str, value: bool) -> ast.Assign:
    return ast.fix_missing_locations(ast.Assign(
        targets=[ast.Name(id=name, ctx=ast.Store())],
        value=ast.Constant(value=value)))


def _not_flags(names: List[str]) -> ast.expr:
    # traced-safe: __pt_not_any__(f1, ...) — a plain `not (f1 or f2)`
    # would bool() traced flags inside the compiled body
    return ast.Call(func=ast.Name(id="__pt_not_any__", ctx=ast.Load()),
                    args=[ast.Name(id=n, ctx=ast.Load()) for n in names],
                    keywords=[])


def _loaded_names(expr: ast.expr) -> Set[str]:
    return {n.id for n in ast.walk(expr)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _definitely_bound(st: ast.stmt) -> Set[str]:
    """Names bound on EVERY path through ``st`` — branch-only bindings must
    not count (state tuples read them unconditionally)."""
    if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = st.targets if isinstance(st, ast.Assign) else [st.target]
        out: Set[str] = set()
        for t in targets:
            for node in ast.walk(t):
                if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                    out.add(node.id)
        return out
    if isinstance(st, ast.If):
        t = set().union(*(_definitely_bound(s) for s in st.body)) \
            if st.body else set()
        f = set().union(*(_definitely_bound(s) for s in st.orelse)) \
            if st.orelse else set()
        return t & f if st.orelse else set()
    if isinstance(st, (ast.Import, ast.ImportFrom)):
        return {a.asname or a.name.split(".")[0] for a in st.names}
    if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
        return {st.name}
    # loops may run zero times; with/try have exceptional paths — nothing
    # is definitely bound by them
    return set()


class _Rewriter:
    def __init__(self, func: ast.FunctionDef):
        self.func = func
        self.counter = 0
        self.applied = 0
        # names bound before a given lineno (params + prior assignments);
        # source-order approximation of definedness
        self.bound: Set[str] = {a.arg for a in func.args.args}
        self.bound |= {a.arg for a in func.args.kwonlyargs}
        if func.args.vararg:
            self.bound.add(func.args.vararg.arg)
        if func.args.kwarg:
            self.bound.add(func.args.kwarg.arg)

    def run(self):
        self.func.body = self._rewrite_block(self.func.body)
        return self.applied

    def _rewrite_block(self, stmts: List[ast.stmt]) -> List[ast.stmt]:
        out: List[ast.stmt] = []
        for st in stmts:
            # recurse into sub-blocks FIRST: an inner tensor loop becomes
            # a plain __pt_while__ call, so the OUTER statement then
            # qualifies too (nested lax control flow composes)
            saved = set(self.bound)
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(st, attr, None)
                if sub:
                    setattr(st, attr, self._rewrite_block(sub))
            self.bound = saved
            replaced = None
            if isinstance(st, ast.While) and not st.orelse:
                replaced = self._try_while(st)
            elif isinstance(st, ast.For) and not st.orelse:
                replaced = self._try_for(st)
            elif isinstance(st, ast.If):
                replaced = self._try_if(st)
            if replaced is None:
                # record only this statement's DEFINITE bindings —
                # branch-only names would make a later generated state
                # tuple read unbound locals
                self.bound = saved | _definitely_bound(st)
                out.append(st)
            else:
                out.extend(replaced)
        return out

    def _maybe_bound(self, name: str, before_lineno: int) -> bool:
        """Whether ``name`` MAY be bound anywhere in the function before
        ``before_lineno`` — the complement of the definitely-bound
        ``self.bound`` (branch-only bindings). Covers Name stores,
        import aliases, def/class statements, and with/except aliases."""
        for node in ast.walk(self.func):
            lineno = getattr(node, "lineno", 10**9)
            if lineno >= before_lineno:
                continue
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store) \
                    and node.id == name:
                return True
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in node.names:
                    bound = alias.asname or alias.name.split(".")[0]
                    if bound == name:
                        return True
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)) and node.name == name:
                return True
            if isinstance(node, ast.ExceptHandler) and node.name == name:
                return True
        return False

    def _state_vars(self, body_names: Set[str], test: ast.expr) -> List[str]:
        vars_ = body_names | (_loaded_names(test) & self.bound)
        return sorted(vars_)

    def _split_temps(self, body: List[ast.stmt], body_names: Set[str],
                     after_lineno: int) -> Optional[Set[str]]:
        """Partition body-assigned names: names NOT bound before the block
        may stay block-local temps iff (a) assigned before first use inside
        the block and (b) never read after the block (zero-iteration reads
        would be NameErrors the transform may not introduce). Returns the
        state-var subset, or None when the block can't be transformed."""
        temps = body_names - self.bound
        if not temps:
            return body_names
        # (b): loaded later in the function (source order)
        for node in ast.walk(self.func):
            if (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)
                    and node.id in temps
                    and getattr(node, "lineno", 0) > after_lineno):
                return None
        # (a): within the block, stores must precede loads per temp
        stored: Set[str] = set()
        for st in body:
            for node in _iter_nodes(st):
                if isinstance(node, ast.FunctionDef) and \
                        node.name.startswith("__pt_"):
                    stored.add(node.name)
                    continue
                if isinstance(node, ast.Name) and node.id in temps:
                    if isinstance(node.ctx, ast.Load) and node.id not in stored:
                        return None
                    if isinstance(node.ctx, ast.Store):
                        stored.add(node.id)
        return body_names - temps

    # -- break/continue lowering (reference: dy2static
    # break_continue_transformer — jumps become boolean state gating the
    # rest of the body and the loop condition) --------------------------

    def _guard_block(self, stmts: List[ast.stmt], brk: str, cont: str):
        """Rewrite Break/Continue into flag assignments; every statement
        after a possible jump is guarded by ``if not (flags):``. Returns
        (new_stmts, used_brk, used_cont) or None when a jump sits inside
        a construct we cannot gate (with/try)."""
        out: List[ast.stmt] = []
        used_b = used_c = False
        for idx, st in enumerate(stmts):
            if isinstance(st, ast.Break):
                out.append(ast.copy_location(_assign_flag(brk, True), st))
                return out, True, used_c  # code after a bare break is dead
            if isinstance(st, ast.Continue):
                out.append(ast.copy_location(_assign_flag(cont, True), st))
                return out, used_b, True
            if isinstance(st, ast.If) and _has_jumps([st]):
                res_t = self._guard_block(st.body, brk, cont)
                res_f = self._guard_block(st.orelse, brk, cont)
                if res_t is None or res_f is None:
                    return None
                st = ast.copy_location(
                    ast.If(test=st.test, body=res_t[0],
                           orelse=res_f[0]), st)
                ast.fix_missing_locations(st)
                used_b |= res_t[1] | res_f[1]
                used_c |= res_t[2] | res_f[2]
                out.append(st)
                rest = self._guard_block(stmts[idx + 1:], brk, cont)
                if rest is None:
                    return None
                rest_stmts, rb, rc = rest
                used_b |= rb
                used_c |= rc
                if rest_stmts:
                    flags = [n for n, u in ((brk, used_b), (cont, used_c))
                             if u]
                    guard = ast.copy_location(ast.If(
                        test=_not_flags(flags), body=rest_stmts, orelse=[]),
                        st)
                    out.append(ast.fix_missing_locations(guard))
                return out, used_b, used_c
            if not isinstance(st, (ast.While, ast.For)) and _has_jumps([st]):
                return None  # jump under with/try/etc: cannot gate
            out.append(st)
        return out, used_b, used_c

    def _lower_jumps(self, node: ast.While):
        """(body, test, prologue) with break/continue lowered, or None."""
        i = self.counter  # flag names share the loop's counter
        brk, cont = f"__pt_brk_{i}", f"__pt_cont_{i}"
        res = self._guard_block(node.body, brk, cont)
        if res is None:
            return None
        body, used_b, used_c = res
        prologue: List[ast.stmt] = []
        test = node.test
        if used_c:
            body = [ast.copy_location(_assign_flag(cont, False), node)] + body
        if used_b:
            prologue.append(ast.copy_location(_assign_flag(brk, False), node))
            test = ast.copy_location(ast.Call(
                func=ast.Name(id="__pt_and_not__", ctx=ast.Load()),
                args=[ast.Name(id=brk, ctx=ast.Load()), node.test],
                keywords=[]), node.test)
            ast.fix_missing_locations(test)
            self.bound.add(brk)
        # the synthesized guards are tensor `if`s over flag state — run
        # them through the if-transform so traced flags become lax.cond.
        # Scope the bound set: body-local bindings must NOT leak into the
        # enclosing _split_temps decision (they are not pre-bound there)
        saved = set(self.bound)
        body = self._rewrite_block(body)
        self.bound = saved
        return body, test, prologue

    def _try_while(self, node: ast.While,
                   min_one_trip: bool = False) -> Optional[List[ast.stmt]]:
        if _has_returns(node.body):
            return None
        prologue: List[ast.stmt] = []
        body, test = node.body, node.test
        if _has_jumps(node.body):
            lowered = self._lower_jumps(node)
            if lowered is None:
                return None
            body, test, prologue = lowered
        node = ast.copy_location(ast.While(test=test, body=body, orelse=[]),
                                 node)
        ast.fix_missing_locations(node)
        end_lineno = getattr(node, "end_lineno", 10**9)
        body_names = _assigned_names(node.body)
        if body_names is None or not body_names:
            return None
        split = self._split_temps(node.body, body_names, end_lineno)
        if split is None and min_one_trip:
            # names defined in the body and read AFTER the loop (e.g. the
            # final ``loss`` of a for-range training loop): peel one
            # guaranteed iteration so they are bound before the lax loop
            # (the reference's UndefinedVar machinery has no XLA analogue
            # — carries need concrete avals)
            import copy as _copy

            peel = [_copy.deepcopy(s) for s in node.body]
            # promote only USER names: generated __pt_* machinery (inner
            # state tuples / branch defs) must stay per-iteration temps —
            # a tuple-valued __pt_st_k in the carry is not a lax aval
            self.bound = self.bound | {
                n for n in set().union(
                    *(_definitely_bound(s) for s in node.body))
                if not n.startswith("__pt_")}
            split = self._split_temps(node.body, body_names, end_lineno)
            if split is not None:
                prologue = prologue + peel
        body_names = split
        if body_names is None or not body_names:
            return None
        vars_ = self._state_vars(body_names, node.test)
        i = self.counter
        self.counter += 1
        tup = ", ".join(vars_) + ("," if len(vars_) == 1 else "")
        src = textwrap.dedent(f"""
            __pt_st_{i} = ({tup})
            def __pt_cond_{i}(__pt_s_{i}):
                ({tup}) = __pt_s_{i}
                return __PT_TEST__
            def __pt_body_{i}(__pt_s_{i}):
                ({tup}) = __pt_s_{i}
                __PT_BODY__
                return ({tup})
            __pt_st_{i} = __pt_while__(__pt_cond_{i}, __pt_body_{i}, __pt_st_{i})
            ({tup}) = __pt_st_{i}
        """)
        block = ast.parse(src).body
        cond_def, body_def = block[1], block[2]
        cond_def.body[1] = ast.Return(value=node.test)
        body_def.body[1:2] = node.body  # replace __PT_BODY__ placeholder
        self.applied += 1
        return [ast.fix_missing_locations(ast.copy_location(s, node))
                for s in prologue + block]

    def _try_for(self, node: ast.For) -> Optional[List[ast.stmt]]:
        """``for v in range(...)`` desugars to an index while (increment
        BEFORE the user body so ``continue`` cannot skip it), then the
        while transform compiles it — XLA folds the counted while into
        fori_loop-style control flow (reference loop_transformer.py:111
        converts gast.For the same way)."""
        it = node.iter
        if not (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "range" and not it.keywords
                and 1 <= len(it.args) <= 3):
            return self._try_for_seq(node)
        if not isinstance(node.target, ast.Name):
            return None
        if _has_returns(node.body):
            return None
        k = self.counter
        iv, stopv, stepv = (f"__pt_fi_{k}", f"__pt_fstop_{k}",
                            f"__pt_fstep_{k}")
        args = it.args
        start = args[0] if len(args) >= 2 else ast.Constant(value=0)
        stop = args[1] if len(args) >= 2 else args[0]
        step = args[2] if len(args) == 3 else ast.Constant(value=1)
        _assign = functools.partial(_assign_stmt, node)

        prologue = [_assign(iv, start), _assign(stopv, stop),
                    _assign(stepv, step)]
        test = ast.fix_missing_locations(ast.copy_location(ast.Call(
            func=ast.Name(id="__pt_range_cont__", ctx=ast.Load()),
            args=[ast.Name(id=iv, ctx=ast.Load()),
                  ast.Name(id=stopv, ctx=ast.Load()),
                  ast.Name(id=stepv, ctx=ast.Load())],
            keywords=[]), node))
        bind_v = _assign(node.target.id, ast.Name(id=iv, ctx=ast.Load()))
        incr = _assign(iv, ast.BinOp(
            left=ast.Name(id=iv, ctx=ast.Load()), op=ast.Add(),
            right=ast.Name(id=stepv, ctx=ast.Load())))
        # constant range with a guaranteed first trip enables one-iteration
        # peeling for body-defined names read after the loop
        const = []
        for a in (start, stop, step):
            const.append(a.value if isinstance(a, ast.Constant)
                         and isinstance(a.value, int) else None)
        if const[2] == 0:
            # range(..., 0) raises ValueError in Python; the desugared
            # direction test would spin forever — keep the original
            return None
        min_one = (None not in const
                   and len(range(const[0], const[1], const[2])) >= 1)

        wl = ast.fix_missing_locations(ast.copy_location(ast.While(
            test=test, body=[bind_v, incr] + node.body, orelse=[]), node))
        saved = set(self.bound)
        self.bound |= {iv, stopv, stepv}
        replaced = self._try_while(wl, min_one_trip=min_one)
        if replaced is None:
            self.bound = saved
            return None
        return prologue + replaced

    def _try_for_seq(self, node: ast.For) -> Optional[List[ast.stmt]]:
        """``for x in seq`` / ``for j, x in enumerate(seq)`` /
        ``for a, b in zip(s1, s2, ...)`` desugar to an index while over
        ``__pt_seq_item__(seq_j, i)`` (reference loop_transformer
        converts iterable For the same way; zip stops at the shortest
        member). The
        iteration count is static (tensor shapes / len()), so the
        constant-trip loop unrolls at trace time — one program, same as
        constant-bound for-range. The payoff is JUMPS: a ``break``/
        ``continue`` on a tensor condition sets a traced flag, the while
        predicate becomes traced mid-loop, and __pt_while__ switches to
        lax.while_loop — ONE compiled program where the plain loop would
        path-specialize per break position. The target is pre-bound to
        element 0 (lax carries need an aval; an empty sequence pre-binds
        an undefined-sentinel and the loop never enters lax)."""
        it = node.iter

        def _tuple_names(target, n):
            if not (isinstance(target, ast.Tuple) and len(target.elts) == n
                    and all(isinstance(e, ast.Name) for e in target.elts)):
                return None
            return [e.id for e in target.elts]

        idx_name = None
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id == "enumerate" and not it.keywords \
                and len(it.args) == 1:
            names = _tuple_names(node.target, 2)
            if names is None:
                return None
            idx_name = names[0]
            pairs = [(names[1], it.args[0])]  # (bind name, seq expr)
        elif isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id == "zip" and not it.keywords \
                and len(it.args) >= 2 \
                and not any(isinstance(a, ast.Starred) for a in it.args):
            names = _tuple_names(node.target, len(it.args))
            if names is None:
                return None
            pairs = list(zip(names, it.args))
        elif isinstance(node.target, ast.Name):
            pairs = [(node.target.id, it)]
        else:
            return None
        if _has_returns(node.body):
            return None
        k = self.counter
        iv, stopv, stepv = f"__pt_fi_{k}", f"__pt_fstop_{k}", f"__pt_fstep_{k}"
        seqvs = [f"__pt_fseq_{k}_{j}" for j in range(len(pairs))]
        _assign = functools.partial(_assign_stmt, node)
        _helper = _helper_call

        prologue = [_assign(sv, ast.Call(
            func=ast.Name(id="__pt_seq_norm__", ctx=ast.Load()),
            args=[expr], keywords=[]))
            for sv, (_, expr) in zip(seqvs, pairs)]
        prologue += [
            _assign(iv, ast.Constant(value=0)),
            # zip stops at the SHORTEST sequence
            _assign(stopv, _helper("__pt_seq_min_len__", *seqvs)),
            _assign(stepv, ast.Constant(value=1)),
        ]
        # pre-bind targets so they can join the loop state tuple — but
        # NOT when already bound: python leaves the existing value
        # untouched on an empty sequence. A name bound only on SOME paths
        # (branch-bound) can't be decided statically: pre-binding would
        # clobber it when the branch ran — decline, the loop stays eager.
        tgt_names = [n for n, _ in pairs] + ([idx_name] if idx_name else [])
        for name in tgt_names:
            if name not in self.bound and self._maybe_bound(name, node.lineno):
                return None
        for (name, _), sv in zip(pairs, seqvs):
            if name not in self.bound:
                prologue.append(_assign(name, _helper("__pt_seq_first__", sv,
                                                      stopv)))
        test = ast.fix_missing_locations(ast.copy_location(
            _helper("__pt_range_cont__", iv, stopv, stepv), node))
        binds = [_assign(name, _helper("__pt_seq_item__", sv, iv))
                 for (name, _), sv in zip(pairs, seqvs)]
        if idx_name is not None:
            binds.append(_assign(idx_name, ast.Name(id=iv, ctx=ast.Load())))
            if idx_name not in self.bound:
                prologue.append(_assign(idx_name, _helper("__pt_seq_fidx__", seqvs[0])))
        incr = _assign(iv, ast.BinOp(
            left=ast.Name(id=iv, ctx=ast.Load()), op=ast.Add(),
            right=ast.Name(id=stepv, ctx=ast.Load())))

        wl = ast.fix_missing_locations(ast.copy_location(ast.While(
            test=test, body=binds + [incr] + node.body, orelse=[]), node))
        saved = set(self.bound)
        self.bound |= {iv, stopv, stepv, *seqvs, *tgt_names}
        replaced = self._try_while(wl)
        if replaced is None:
            self.bound = saved
            return None
        return prologue + replaced

    def _try_if(self, node: ast.If) -> Optional[List[ast.stmt]]:
        if _has_jumps(node.body) or _has_jumps(node.orelse):
            return None
        tnames = _assigned_names(node.body)
        fnames = _assigned_names(node.orelse) if node.orelse else set()
        if tnames is None or fnames is None:
            return None
        end = getattr(node, "end_lineno", 10**9)
        tnames = self._split_temps(node.body, tnames, end)
        fnames = self._split_temps(node.orelse, fnames, end) \
            if node.orelse else fnames
        if tnames is None or fnames is None:
            return None
        body_names = tnames | fnames
        if not body_names:
            return None
        vars_ = self._state_vars(body_names, node.test)
        i = self.counter
        self.counter += 1
        tup = ", ".join(vars_) + ("," if len(vars_) == 1 else "")
        src = textwrap.dedent(f"""
            __pt_st_{i} = ({tup})
            def __pt_true_{i}(__pt_s_{i}):
                ({tup}) = __pt_s_{i}
                __PT_BODY__
                return ({tup})
            def __pt_false_{i}(__pt_s_{i}):
                ({tup}) = __pt_s_{i}
                __PT_ELSE__
                return ({tup})
            __pt_st_{i} = __pt_if__(__PT_TEST__, __pt_true_{i}, __pt_false_{i}, __pt_st_{i})
            ({tup}) = __pt_st_{i}
        """)
        block = ast.parse(src).body
        true_def, false_def, call_stmt = block[1], block[2], block[3]
        true_def.body[1:2] = node.body
        if node.orelse:
            false_def.body[1:2] = node.orelse
        else:
            del false_def.body[1]
        call_stmt.value.args[0] = node.test
        self.applied += 1
        return [ast.fix_missing_locations(ast.copy_location(s, node))
                for s in block]


def transform_control_flow(fn: Callable) -> Optional[Callable]:
    """Return a variant of ``fn`` whose simple while/if statements route
    through __pt_while__/__pt_if__, or None when nothing applies (no
    source, closures, or no eligible statement)."""
    if getattr(fn, "__closure__", None):
        return None  # freevars would be lost on re-exec
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return None
    if not tree.body or not isinstance(tree.body[0], ast.FunctionDef):
        return None
    func = tree.body[0]
    func.decorator_list = []
    if _rewrite(func) == 0:
        return None
    ast.fix_missing_locations(tree)
    code = compile(tree, filename=f"<dy2static {fn.__name__}>", mode="exec")
    # live-globals proxy: helpers resolve locally, everything else falls
    # through to fn's REAL module globals — forward references defined
    # after decoration and test monkeypatching keep working
    glb = _GlobalsProxy(fn.__globals__,
                        {"__pt_while__": _pt_while, "__pt_if__": _pt_if,
                         "__pt_range_cont__": _pt_range_cont,
                         "__pt_and_not__": _pt_and_not,
                         "__pt_not_any__": _pt_not_any,
                         "__pt_seq_min_len__": _pt_seq_min_len,
                         "__pt_seq_fidx__": _pt_seq_fidx,
                         "__pt_seq_first__": _pt_seq_first,
                         "__pt_seq_item__": _pt_seq_item,
                         "__pt_seq_norm__": _pt_seq_norm})
    loc: dict = {}
    exec(code, glb, loc)
    new_fn = loc[func.name]
    new_fn.__defaults__ = fn.__defaults__
    new_fn.__kwdefaults__ = fn.__kwdefaults__
    return new_fn


class _GlobalsProxy(dict):
    """exec globals that overlay helper names on a LIVE base dict
    (CPython consults __missing__ for dict-subclass globals)."""

    def __init__(self, base: dict, extra: dict):
        super().__init__(extra)
        self._base = base

    def __missing__(self, key):
        return self._base[key]


def _rewrite(func: ast.FunctionDef) -> int:
    return _Rewriter(func).run()
