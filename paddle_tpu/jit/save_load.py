"""jit.save / jit.load — serialize a traced program + parameters to disk.

Parity: python/paddle/jit/api.py (``paddle.jit.save``/``load``) and the C++
re-loadable program of paddle/fluid/jit/. TPU design: the "program" artifact
is a serialized StableHLO module produced by ``jax.export`` (the analogue of
the reference's ProgramDesc/PIR file), with parameters/buffers held as
*inputs* of the exported computation and stored beside it in an ``.npz`` —
mirroring the reference's ``.pdmodel`` + ``.pdiparams`` split so params can
be swapped without re-tracing.

Artifacts written for ``paddle_tpu.jit.save(layer, "m")``:
  m.pdmodel    — serialized jax.export.Exported (StableHLO + in/out trees)
  m.pdiparams  — npz of parameters and buffers (flat key → array)
  m.pdmeta     — json: input specs, param/buffer key lists, output tree
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Optional, Sequence

import jax
import numpy as np
from jax import export as jexport

from ..core.tensor import Tensor
from ..static.input_spec import InputSpec, avals_from_specs
from .api import StaticFunction, _LayerStaticWrapper, _TraceScope, _wrap_in, _unwrap_out
from ..core.autograd import no_grad

_MODEL_SUFFIX = ".pdmodel"
_PARAMS_SUFFIX = ".pdiparams"
_META_SUFFIX = ".pdmeta"


def _specs_from_args(args) -> list:
    specs = []
    for a in args:
        if isinstance(a, InputSpec):
            specs.append(a)
        elif isinstance(a, Tensor):
            specs.append(InputSpec(tuple(a.shape), str(np.dtype(a._data.dtype))))
        elif isinstance(a, (np.ndarray, jax.Array)):
            specs.append(InputSpec(tuple(a.shape), str(a.dtype)))
        else:
            raise TypeError(f"jit.save input_spec entries must be InputSpec/Tensor/ndarray, got {type(a)}")
    return specs


def save(layer, path: str, input_spec: Optional[Sequence] = None, **configs) -> None:
    """Save a Layer / to_static function as program + params artifacts."""
    from ..nn.layer import Layer

    target = layer
    if isinstance(target, _LayerStaticWrapper):
        target = target._layer
    if isinstance(target, StaticFunction):
        if input_spec is None:
            raise ValueError("jit.save of a function requires input_spec.")
        specs = _specs_from_args(input_spec)
        avals = avals_from_specs(specs)
        fn = target._fn

        def runner(params, buffers, *datas):
            del params, buffers
            with _TraceScope(), no_grad():
                out = fn(*[_wrap_in(d) for d in datas])
                return jax.tree.map(_unwrap_out, out, is_leaf=lambda x: isinstance(x, Tensor))

        params, buffers = {}, {}
    elif isinstance(target, Layer):
        if input_spec is None:
            raise ValueError("jit.save of a Layer requires input_spec.")
        specs = _specs_from_args(input_spec)
        avals = avals_from_specs(specs)
        params = {k: np.asarray(v._data) for k, v in target.named_parameters_dict().items()}
        buffers = {k: np.asarray(v._data) for k, v in target.named_buffers_dict().items()}

        def runner(params, buffers, *datas):
            with _TraceScope(), no_grad():
                from ..utils.functional import functional_call

                merged = {k: Tensor(v) for k, v in {**params, **buffers}.items()}
                out = functional_call(target, merged, *[_wrap_in(d) for d in datas])
                return jax.tree.map(_unwrap_out, out, is_leaf=lambda x: isinstance(x, Tensor))
    else:
        raise TypeError(f"jit.save expects a Layer or to_static function, got {type(layer)}")

    param_sds = {k: jax.ShapeDtypeStruct(v.shape, v.dtype) for k, v in params.items()}
    buffer_sds = {k: jax.ShapeDtypeStruct(v.shape, v.dtype) for k, v in buffers.items()}
    exported = jexport.export(jax.jit(runner))(param_sds, buffer_sds, *avals)

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path + _MODEL_SUFFIX, "wb") as f:
        f.write(exported.serialize())
    with open(path + _PARAMS_SUFFIX, "wb") as f:
        np.savez(f, **{("p:" + k): v for k, v in params.items()},
                 **{("b:" + k): v for k, v in buffers.items()})
    with open(path + _META_SUFFIX, "w") as f:
        json.dump({
            "input_specs": [s.to_dict() for s in specs],
            "params": sorted(params.keys()),
            "buffers": sorted(buffers.keys()),
            "format": "paddle_tpu.jit.v1",
        }, f)


class TranslatedLayer:
    """A loaded program — callable like the original Layer (inference only).

    Parity: python/paddle/jit/translated_layer.py TranslatedLayer; here the
    body is a deserialized StableHLO executable invoked through
    ``Exported.call`` (re-jitted once, then cached by XLA).
    """

    def __init__(self, exported, params: dict, buffers: dict, meta: dict):
        self._exported = exported
        self._params = {k: jax.numpy.asarray(v) for k, v in params.items()}
        self._buffers = {k: jax.numpy.asarray(v) for k, v in buffers.items()}
        self._meta = meta
        self._jitted = jax.jit(exported.call)

    @property
    def input_specs(self):
        return [InputSpec.from_dict(d) for d in self._meta.get("input_specs", [])]

    def state_dict(self):
        return {k: Tensor(v) for k, v in {**self._params, **self._buffers}.items()}

    def set_state_dict(self, state_dict):
        for k, v in state_dict.items():
            arr = v._data if isinstance(v, Tensor) else jax.numpy.asarray(v)
            if k in self._params:
                self._params[k] = arr
            elif k in self._buffers:
                self._buffers[k] = arr

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("TranslatedLayer is inference-only; retrain the source Layer instead.")

    def __call__(self, *args):
        datas = [a._data if isinstance(a, Tensor) else jax.numpy.asarray(a) for a in args]
        out = self._jitted(self._params, self._buffers, *datas)
        return jax.tree.map(lambda x: Tensor(x) if isinstance(x, jax.Array) else x, out)

    forward = __call__


def load(path: str, **configs) -> TranslatedLayer:
    """Load artifacts written by jit.save into a callable TranslatedLayer."""
    with open(path + _MODEL_SUFFIX, "rb") as f:
        exported = jexport.deserialize(bytearray(f.read()))
    with open(path + _META_SUFFIX) as f:
        meta = json.load(f)
    params, buffers = {}, {}
    with np.load(path + _PARAMS_SUFFIX) as z:
        for k in z.files:
            kind, name = k.split(":", 1)
            (params if kind == "p" else buffers)[name] = z[k]
    return TranslatedLayer(exported, params, buffers, meta)
