"""paddle_tpu.profiler — unified host+device profiler.

Reference parity (SURVEY §5.1): python/paddle/profiler/profiler.py:358
(Profiler with scheduler states ProfilerState:89, targets), RecordEvent
instrumentation (paddle/fluid/platform/profiler/event_tracing.h:43),
ChromeTracingLogger export (chrometracing_logger.h:32), summary statistics
(profiler_statistic.py) and the benchmark ips timer (timer.py).

TPU design: host spans go through the native C++ ring-buffer tracer
(csrc/host_tracer.cc) — the HostTracer equivalent; device activity comes
from jax.profiler (XLA/PJRT xplane traces, the CudaTracer slot). Both are
surfaced as chrome-trace JSON.
"""

from __future__ import annotations

import json
import os
import threading
import time
from enum import IntEnum
from typing import Callable, Dict, List, Optional

from ..core.native import get_native

__all__ = [
    "Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
    "TracerEventType", "make_scheduler", "export_chrome_tracing", "benchmark",
]


class ProfilerState(IntEnum):
    # reference: profiler.py ProfilerState:89
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(IntEnum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


class TracerEventType(IntEnum):
    # reference: paddle/fluid/platform/profiler/trace_event.h categories
    Operator = 0
    Dataloader = 1
    ProfileStep = 2
    Forward = 3
    Backward = 4
    Optimization = 5
    Communication = 6
    PythonUserDefined = 7


# ---------------------------------------------------------------------------
# RecordEvent: host span instrumentation
# ---------------------------------------------------------------------------

_py_events: List[tuple] = []  # fallback when no native tracer
_py_events_lock = threading.Lock()
_recording = [False]  # single source of truth; dispatch.py imports this list

# observability.StepTelemetry installs itself here (attach_benchmark) so
# the ips timer's per-step measurements feed the telemetry stream; the
# None check is the whole cost when nothing is attached.
_telemetry_sink = [None]

# observability.tracing installs itself here (attach_profiler_spans) so
# completed RecordEvent spans also land in the request-trace buffer —
# one /trace export carries request lifecycle AND step-internal spans
# on the shared perf_counter_ns clock. Detached (the default) costs one
# list-index check per span.
_trace_sink = [None]


class RecordEvent:
    """Span context manager/decorator (reference event_tracing.h RecordEvent).

    with profiler.RecordEvent("data_load"):
        ...
    """

    def __init__(self, name: str, event_type: TracerEventType = TracerEventType.PythonUserDefined):
        self.name = name
        self.event_type = event_type
        self._id = None
        self._t0 = None

    def begin(self):
        if _trace_sink[0] is not None:
            # tracing interop records host timestamps even on the native
            # path (the C++ ring keeps its own) and even when the
            # profiler itself is CLOSED — a serving box traces without
            # running a profiler session
            self._trace_t0 = time.perf_counter_ns()
        if not _recording[0]:
            return
        lib = get_native()
        if lib is not None:
            self._id = lib.pth_record_begin(self.name.encode(), int(self.event_type))
        else:
            self._t0 = time.perf_counter_ns()

    def end(self):
        sink = _trace_sink[0]
        if sink is not None and getattr(self, "_trace_t0", None) is not None:
            try:
                sink(self.name, self._trace_t0, time.perf_counter_ns(),
                     int(self.event_type))
            except Exception:
                pass  # tracing must never break instrumented code
            self._trace_t0 = None
        if not _recording[0]:
            return
        lib = get_native()
        if lib is not None:
            if self._id is not None:
                lib.pth_record_end(self._id)
                self._id = None
        elif self._t0 is not None:
            with _py_events_lock:
                _py_events.append((self.name, threading.get_ident(),
                                   self._t0, time.perf_counter_ns(),
                                   int(self.event_type)))
            self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False

    def __call__(self, fn):
        def wrapper(*args, **kwargs):
            with RecordEvent(self.name, self.event_type):
                return fn(*args, **kwargs)
        return wrapper


def _drain_events() -> List[Dict]:
    """Drain all completed spans → list of dicts (ns timestamps)."""
    out = []
    lib = get_native()
    if lib is not None:
        import ctypes

        class _Event(ctypes.Structure):
            _fields_ = [("name", ctypes.c_char * 64), ("tid", ctypes.c_uint64),
                        ("start_ns", ctypes.c_uint64), ("end_ns", ctypes.c_uint64),
                        ("category", ctypes.c_uint32), ("_pad", ctypes.c_uint32)]

        n = lib.pth_tracer_count()
        if n:
            buf = (_Event * n)()
            got = lib.pth_tracer_drain(buf, n)
            for e in buf[:got]:
                out.append({"name": e.name.decode(), "tid": int(e.tid),
                            "start_ns": int(e.start_ns), "end_ns": int(e.end_ns),
                            "category": int(e.category)})
    with _py_events_lock:
        for name, tid, t0, t1, cat in _py_events:
            out.append({"name": name, "tid": tid, "start_ns": t0, "end_ns": t1,
                        "category": cat})
        _py_events.clear()
    out.sort(key=lambda e: e["start_ns"])
    return out


# ---------------------------------------------------------------------------
# Scheduler / export helpers
# ---------------------------------------------------------------------------


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """Reference: profiler.py make_scheduler — step-indexed state machine."""
    period = closed + ready + record

    def schedule(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


def _default_scheduler(_step: int) -> ProfilerState:
    return ProfilerState.RECORD


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None) -> Callable:
    """on_trace_ready callback writing chrome://tracing JSON."""

    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}_time_{int(time.time())}.paddle_trace.json")
        prof.export(path)

    return handler


def _to_chrome_trace(events: List[Dict]) -> Dict:
    pid = os.getpid()
    trace = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
              "args": {"name": "paddle_tpu host"}}]
    for e in events:
        cat = e.get("category", 7)
        try:
            cat = TracerEventType(cat).name
        except ValueError:
            cat = str(cat)
        trace.append({
            "name": e["name"], "ph": "X", "pid": pid, "tid": e["tid"] % 100000,
            "ts": e["start_ns"] / 1000.0,
            "dur": max(e["end_ns"] - e["start_ns"], 0) / 1000.0,
            "cat": cat,
        })
    return {"traceEvents": trace}


# ---------------------------------------------------------------------------
# Profiler
# ---------------------------------------------------------------------------


class Profiler:
    """Reference-shaped profiler (profiler.py:358).

    prof = Profiler(scheduler=make_scheduler(closed=1, ready=1, record=2))
    prof.start(); loop: work; prof.step(); prof.stop()
    """

    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only: bool = False, record_shapes: bool = False,
                 profile_memory: bool = False, with_flops: bool = False,
                 ring_capacity: int = 1 << 16):
        self.targets = targets or [ProfilerTarget.CPU]
        if scheduler is None:
            self.scheduler = _default_scheduler
        elif isinstance(scheduler, (tuple, list)):
            lo, hi = scheduler
            self.scheduler = make_scheduler(closed=max(lo, 0), ready=0,
                                            record=hi - lo, repeat=1)
        else:
            self.scheduler = scheduler
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.profile_memory = profile_memory
        self._ring_capacity = ring_capacity
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self._events: List[Dict] = []
        self._mem_records: List[Dict] = []
        self._device_trace_dir: Optional[str] = None
        self._timer = benchmark()

    def _record_memory(self):
        """profile_memory=True: device live/peak bytes at this step, into
        the observability watermark gauges + a per-step record that
        summary() renders."""
        from ..observability.telemetry import record_memory_gauges

        live, peak = record_memory_gauges()
        self._mem_records.append(
            {"step": self.step_num, "live_bytes": live, "peak_bytes": peak})

    # -- state machine -----------------------------------------------------
    def start(self):
        self._timer.begin()
        if self.timer_only:
            return
        lib = get_native()
        if lib is not None:
            lib.pth_tracer_init(self._ring_capacity)
        self._apply_state(self.scheduler(self.step_num))

    def _apply_state(self, state: ProfilerState):
        prev = self.current_state
        self.current_state = state
        should_record = state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        was_recording = prev in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        if should_record and not was_recording:
            _recording[0] = True
            lib = get_native()
            if lib is not None:
                lib.pth_tracer_enable(1)
        elif was_recording and not should_record:
            self._collect()
        # RECORD -> RECORD_AND_RETURN needs no action here; the cycle
        # boundary (collect + on_trace_ready) happens in step()

    def _collect(self):
        _recording[0] = False
        lib = get_native()
        if lib is not None:
            lib.pth_tracer_enable(0)
        self._events.extend(_drain_events())

    def step(self, num_samples: Optional[int] = None):
        self._timer.step(num_samples)
        if self.profile_memory:
            self._record_memory()
        if self.timer_only:
            return
        if self.current_state == ProfilerState.RECORD_AND_RETURN:
            self._collect()
            if self.on_trace_ready:
                self.on_trace_ready(self)
            _recording[0] = False
            # cycle boundary: next _apply_state must see "not recording" so
            # back-to-back record phases re-enable the tracer
            self.current_state = ProfilerState.CLOSED
        self.step_num += 1
        self._apply_state(self.scheduler(self.step_num))

    def stop(self):
        self._timer.end()
        if self.timer_only:
            return
        if self.current_state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            self._collect()
            if self.on_trace_ready:
                self.on_trace_ready(self)
        self.current_state = ProfilerState.CLOSED
        _recording[0] = False

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- results -----------------------------------------------------------
    def events(self) -> List[Dict]:
        return list(self._events)

    def export(self, path: str, format: str = "json"):
        with open(path, "w") as f:
            json.dump(_to_chrome_trace(self._events), f)

    def summary(self, sorted_by: str = "total", **kwargs) -> str:
        """Op-level aggregate table (reference profiler_statistic.py)."""
        agg: Dict[str, List[float]] = {}
        for e in self._events:
            dur_us = (e["end_ns"] - e["start_ns"]) / 1000.0
            agg.setdefault(e["name"], []).append(dur_us)
        rows = [(name, len(ds), sum(ds), sum(ds) / len(ds), max(ds), min(ds))
                for name, ds in agg.items()]
        key = {"total": 2, "calls": 1, "avg": 3, "max": 4, "min": 5}.get(sorted_by, 2)
        rows.sort(key=lambda r: r[key], reverse=True)
        lines = [f"{'Name':<40}{'Calls':>8}{'Total(us)':>14}{'Avg(us)':>12}"
                 f"{'Max(us)':>12}{'Min(us)':>12}"]
        for r in rows:
            lines.append(f"{r[0]:<40}{r[1]:>8}{r[2]:>14.1f}{r[3]:>12.1f}"
                         f"{r[4]:>12.1f}{r[5]:>12.1f}")
        if self._mem_records:
            mb = 1.0 / 2 ** 20
            known = [r for r in self._mem_records
                     if r["peak_bytes"] is not None]
            lines.append("")
            lines.append(f"{'Device memory (profile_memory=True)':<40}"
                         f"{'Steps':>8}{'Peak(MB)':>14}{'LastLive(MB)':>14}")
            if known:
                peak = max(r["peak_bytes"] for r in known)
                live = next((r["live_bytes"] for r in reversed(known)
                             if r["live_bytes"] is not None), 0) or 0
                lines.append(f"{'':<40}{len(self._mem_records):>8}"
                             f"{peak * mb:>14.1f}{live * mb:>14.1f}")
            else:
                from ..observability.perf import \
                    PJRT_MEMORY_UNSUPPORTED_NOTE

                lines.append(f"{'':<40}{len(self._mem_records):>8}"
                             f"{PJRT_MEMORY_UNSUPPORTED_NOTE:>28}")
        return "\n".join(lines)

    def memory_records(self) -> List[Dict]:
        """Per-step device-memory watermarks (profile_memory=True)."""
        return list(self._mem_records)

    # -- device (XLA/PJRT) traces -------------------------------------------
    def start_device_trace(self, log_dir: str):
        """Capture XLA device activity via jax.profiler (xplane), viewable in
        TensorBoard/XProf — the CudaTracer slot of the reference design."""
        import jax

        self._device_trace_dir = log_dir
        jax.profiler.start_trace(log_dir)

    def stop_device_trace(self):
        if self._device_trace_dir is not None:
            import jax

            jax.profiler.stop_trace()
            self._device_trace_dir = None


# ---------------------------------------------------------------------------
# benchmark timer (reference profiler/timer.py — ips with warmup skip)
# ---------------------------------------------------------------------------


class _Benchmark:
    def __init__(self):
        self.reset()

    def reset(self):
        self._last = None
        self._step_times: List[float] = []
        self._samples: List[Optional[int]] = []
        self._running = False

    def begin(self):
        self.reset()
        self._running = True
        self._last = time.perf_counter()

    def step(self, num_samples: Optional[int] = None):
        if not self._running:
            return
        now = time.perf_counter()
        dt = now - self._last
        self._step_times.append(dt)
        self._samples.append(num_samples)
        self._last = now
        sink = _telemetry_sink[0]
        if sink is not None:
            sink.step(num_samples=num_samples, step_time=dt)

    def end(self):
        self._running = False

    def step_info(self, unit: str = "samples") -> str:
        s = self.speed_average()
        avg = (sum(self._step_times) / len(self._step_times)) if self._step_times else 0.0
        return f"avg_step_time: {avg*1000:.2f} ms, ips: {s:.2f} {unit}/s"

    def speed_average(self, skip: int = 1) -> float:
        """ips, skipping the first `skip` (warmup/compile) steps."""
        times = self._step_times[skip:] or self._step_times
        samples = self._samples[skip:] or self._samples
        if not times:
            return 0.0
        total_t = sum(times)
        if any(s is None for s in samples):
            return len(times) / total_t if total_t else 0.0
        return sum(samples) / total_t if total_t else 0.0


_benchmark = _Benchmark()


def benchmark() -> _Benchmark:
    return _benchmark
