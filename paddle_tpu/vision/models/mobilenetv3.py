"""MobileNetV3 small/large (parity: python/paddle/vision/models/mobilenetv3.py)."""

from __future__ import annotations

from ... import nn
from ...ops.manipulation import flatten


def _make_divisible(v, divisor=8, min_value=None):
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _SqueezeExcite(nn.Layer):
    def __init__(self, channels, reduction=4):
        super().__init__()
        mid = _make_divisible(channels // reduction)
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc1 = nn.Conv2D(channels, mid, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(mid, channels, 1)
        self.hsigmoid = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsigmoid(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _ConvBNAct(nn.Layer):
    def __init__(self, cin, cout, kernel, stride=1, groups=1, act=None):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, kernel, stride=stride,
                              padding=(kernel - 1) // 2, groups=groups, bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)
        self.act = act() if act is not None else None

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act is not None else x


class _InvertedResidual(nn.Layer):
    def __init__(self, cin, cmid, cout, kernel, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and cin == cout
        layers = []
        if cmid != cin:
            layers.append(_ConvBNAct(cin, cmid, 1, act=act))
        layers.append(_ConvBNAct(cmid, cmid, kernel, stride=stride, groups=cmid, act=act))
        if use_se:
            layers.append(_SqueezeExcite(cmid))
        layers.append(_ConvBNAct(cmid, cout, 1, act=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


# (kernel, exp, out, use_se, act, stride); act: 'RE' relu / 'HS' hardswish
_LARGE = [
    (3, 16, 16, False, "RE", 1), (3, 64, 24, False, "RE", 2), (3, 72, 24, False, "RE", 1),
    (5, 72, 40, True, "RE", 2), (5, 120, 40, True, "RE", 1), (5, 120, 40, True, "RE", 1),
    (3, 240, 80, False, "HS", 2), (3, 200, 80, False, "HS", 1), (3, 184, 80, False, "HS", 1),
    (3, 184, 80, False, "HS", 1), (3, 480, 112, True, "HS", 1), (3, 672, 112, True, "HS", 1),
    (5, 672, 160, True, "HS", 2), (5, 960, 160, True, "HS", 1), (5, 960, 160, True, "HS", 1),
]
_SMALL = [
    (3, 16, 16, True, "RE", 2), (3, 72, 24, False, "RE", 2), (3, 88, 24, False, "RE", 1),
    (5, 96, 40, True, "HS", 2), (5, 240, 40, True, "HS", 1), (5, 240, 40, True, "HS", 1),
    (5, 120, 48, True, "HS", 1), (5, 144, 48, True, "HS", 1), (5, 288, 96, True, "HS", 2),
    (5, 576, 96, True, "HS", 1), (5, 576, 96, True, "HS", 1),
]


class MobileNetV3(nn.Layer):
    def __init__(self, config, last_channel, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        cin = _make_divisible(16 * scale)
        self.conv1 = _ConvBNAct(3, cin, 3, stride=2, act=nn.Hardswish)
        blocks = []
        for kernel, exp, cout, use_se, act_name, stride in config:
            act = nn.ReLU if act_name == "RE" else nn.Hardswish
            cmid = _make_divisible(exp * scale)
            cout = _make_divisible(cout * scale)
            blocks.append(_InvertedResidual(cin, cmid, cout, kernel, stride, use_se, act))
            cin = cout
        self.blocks = nn.Sequential(*blocks)
        clast = _make_divisible(config[-1][1] * scale)
        self.conv2 = _ConvBNAct(cin, clast, 1, act=nn.Hardswish)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(clast, last_channel),
                nn.Hardswish(),
                nn.Dropout(0.2),
                nn.Linear(last_channel, num_classes),
            )

    def forward(self, x):
        x = self.conv2(self.blocks(self.conv1(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = flatten(x, 1)
            x = self.classifier(x)
        return x


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, last_channel=1280, scale=scale,
                         num_classes=num_classes, with_pool=with_pool)


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, last_channel=1024, scale=scale,
                         num_classes=num_classes, with_pool=with_pool)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access; load weights via set_state_dict")
    return MobileNetV3Large(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access; load weights via set_state_dict")
    return MobileNetV3Small(scale=scale, **kwargs)
