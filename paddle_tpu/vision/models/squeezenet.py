"""SqueezeNet (parity: python/paddle/vision/models/squeezenet.py)."""

from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat, flatten


class _Fire(nn.Layer):
    def __init__(self, cin, squeeze, expand1x1, expand3x3):
        super().__init__()
        self.squeeze = nn.Conv2D(cin, squeeze, 1)
        self.relu = nn.ReLU()
        self.expand1x1 = nn.Conv2D(squeeze, expand1x1, 1)
        self.expand3x3 = nn.Conv2D(squeeze, expand3x3, 3, padding=1)

    def forward(self, x):
        s = self.relu(self.squeeze(x))
        return concat([self.relu(self.expand1x1(s)), self.relu(self.expand3x3(s))], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.1", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.version = version

        if version == "1.0":
            self.conv1 = nn.Conv2D(3, 96, 7, stride=2)
            fires = [
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64), _Fire(128, 32, 128, 128),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256),
            ]
            self._pool_after = {2, 6}  # 1.0 layout: pool after 3rd and 7th fire
        elif version == "1.1":
            self.conv1 = nn.Conv2D(3, 64, 3, stride=2, padding=1)
            fires = [
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64), _Fire(128, 32, 128, 128),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256),
            ]
            self._pool_after = {1, 3}
        else:
            raise ValueError(f"unsupported SqueezeNet version {version}")
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2)
        self.fires = nn.LayerList(fires)
        self.dropout = nn.Dropout(0.5)
        self.final_conv = nn.Conv2D(512, num_classes if num_classes > 0 else 1000, 1)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))

    def forward(self, x):
        x = self.maxpool(self.relu(self.conv1(x)))
        for i, fire in enumerate(self.fires):
            x = fire(x)
            if i in self._pool_after:
                x = self.maxpool(x)
        if self.num_classes > 0:
            x = self.relu(self.final_conv(self.dropout(x)))
        if self.with_pool:
            x = self.pool(x)
            x = flatten(x, 1)
        return x


def squeezenet1_0(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access; load weights via set_state_dict")
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access; load weights via set_state_dict")
    return SqueezeNet("1.1", **kwargs)
