"""GoogLeNet / Inception v1 (parity: python/paddle/vision/models/googlenet.py)."""

from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat, flatten


class Inception(nn.Layer):
    def __init__(self, in_channels, ch1x1, ch3x3red, ch3x3, ch5x5red, ch5x5, pool_proj):
        super().__init__()
        self.branch1 = nn.Sequential(nn.Conv2D(in_channels, ch1x1, 1), nn.ReLU())
        self.branch2 = nn.Sequential(
            nn.Conv2D(in_channels, ch3x3red, 1), nn.ReLU(),
            nn.Conv2D(ch3x3red, ch3x3, 3, padding=1), nn.ReLU())
        self.branch3 = nn.Sequential(
            nn.Conv2D(in_channels, ch5x5red, 1), nn.ReLU(),
            nn.Conv2D(ch5x5red, ch5x5, 5, padding=2), nn.ReLU())
        self.branch4 = nn.Sequential(
            nn.MaxPool2D(3, stride=1, padding=1),
            nn.Conv2D(in_channels, pool_proj, 1), nn.ReLU())

    def forward(self, x):
        return concat([self.branch1(x), self.branch2(x), self.branch3(x), self.branch4(x)], axis=1)


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = nn.Sequential(nn.Conv2D(3, 64, 7, stride=2, padding=3), nn.ReLU())
        self.pool1 = nn.MaxPool2D(3, stride=2, padding=1)
        self.conv2 = nn.Sequential(nn.Conv2D(64, 64, 1), nn.ReLU())
        self.conv3 = nn.Sequential(nn.Conv2D(64, 192, 3, padding=1), nn.ReLU())
        self.pool3 = nn.MaxPool2D(3, stride=2, padding=1)
        self.inception3a = Inception(192, 64, 96, 128, 16, 32, 32)
        self.inception3b = Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool4 = nn.MaxPool2D(3, stride=2, padding=1)
        self.inception4a = Inception(480, 192, 96, 208, 16, 48, 64)
        self.inception4b = Inception(512, 160, 112, 224, 24, 64, 64)
        self.inception4c = Inception(512, 128, 128, 256, 24, 64, 64)
        self.inception4d = Inception(512, 112, 144, 288, 32, 64, 64)
        self.inception4e = Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool5 = nn.MaxPool2D(3, stride=2, padding=1)
        self.inception5a = Inception(832, 256, 160, 320, 32, 128, 128)
        self.inception5b = Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.dropout = nn.Dropout(0.2)
            self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.pool1(self.conv1(x))
        x = self.pool3(self.conv3(self.conv2(x)))
        x = self.pool4(self.inception3b(self.inception3a(x)))
        x = self.inception4e(self.inception4d(self.inception4c(self.inception4b(self.inception4a(x)))))
        x = self.pool5(x)
        x = self.inception5b(self.inception5a(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = flatten(x, 1)
            x = self.fc(self.dropout(x))
        return x


def googlenet(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("load pretrained weights via set_state_dict")
    return GoogLeNet(**kwargs)
