"""InceptionV3 (parity: python/paddle/vision/models/inceptionv3.py)."""

from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat, flatten


class _ConvBNAct(nn.Layer):
    def __init__(self, cin, cout, kernel, stride=1, padding=0):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, kernel, stride=stride, padding=padding, bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class _InceptionA(nn.Layer):
    def __init__(self, cin, pool_features):
        super().__init__()
        self.branch1x1 = _ConvBNAct(cin, 64, 1)
        self.branch5x5 = nn.Sequential(_ConvBNAct(cin, 48, 1), _ConvBNAct(48, 64, 5, padding=2))
        self.branch3x3dbl = nn.Sequential(_ConvBNAct(cin, 64, 1), _ConvBNAct(64, 96, 3, padding=1),
                                          _ConvBNAct(96, 96, 3, padding=1))
        self.pool = nn.AvgPool2D(3, stride=1, padding=1)
        self.branch_pool = _ConvBNAct(cin, pool_features, 1)

    def forward(self, x):
        return concat([self.branch1x1(x), self.branch5x5(x), self.branch3x3dbl(x),
                       self.branch_pool(self.pool(x))], axis=1)


class _InceptionB(nn.Layer):
    def __init__(self, cin):
        super().__init__()
        self.branch3x3 = _ConvBNAct(cin, 384, 3, stride=2)
        self.branch3x3dbl = nn.Sequential(_ConvBNAct(cin, 64, 1), _ConvBNAct(64, 96, 3, padding=1),
                                          _ConvBNAct(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.branch3x3(x), self.branch3x3dbl(x), self.pool(x)], axis=1)


class _InceptionC(nn.Layer):
    def __init__(self, cin, c7):
        super().__init__()
        self.branch1x1 = _ConvBNAct(cin, 192, 1)
        self.branch7x7 = nn.Sequential(
            _ConvBNAct(cin, c7, 1),
            _ConvBNAct(c7, c7, (1, 7), padding=(0, 3)),
            _ConvBNAct(c7, 192, (7, 1), padding=(3, 0)),
        )
        self.branch7x7dbl = nn.Sequential(
            _ConvBNAct(cin, c7, 1),
            _ConvBNAct(c7, c7, (7, 1), padding=(3, 0)),
            _ConvBNAct(c7, c7, (1, 7), padding=(0, 3)),
            _ConvBNAct(c7, c7, (7, 1), padding=(3, 0)),
            _ConvBNAct(c7, 192, (1, 7), padding=(0, 3)),
        )
        self.pool = nn.AvgPool2D(3, stride=1, padding=1)
        self.branch_pool = _ConvBNAct(cin, 192, 1)

    def forward(self, x):
        return concat([self.branch1x1(x), self.branch7x7(x), self.branch7x7dbl(x),
                       self.branch_pool(self.pool(x))], axis=1)


class _InceptionD(nn.Layer):
    def __init__(self, cin):
        super().__init__()
        self.branch3x3 = nn.Sequential(_ConvBNAct(cin, 192, 1), _ConvBNAct(192, 320, 3, stride=2))
        self.branch7x7x3 = nn.Sequential(
            _ConvBNAct(cin, 192, 1),
            _ConvBNAct(192, 192, (1, 7), padding=(0, 3)),
            _ConvBNAct(192, 192, (7, 1), padding=(3, 0)),
            _ConvBNAct(192, 192, 3, stride=2),
        )
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.branch3x3(x), self.branch7x7x3(x), self.pool(x)], axis=1)


class _InceptionE(nn.Layer):
    def __init__(self, cin):
        super().__init__()
        self.branch1x1 = _ConvBNAct(cin, 320, 1)
        self.branch3x3_1 = _ConvBNAct(cin, 384, 1)
        self.branch3x3_2a = _ConvBNAct(384, 384, (1, 3), padding=(0, 1))
        self.branch3x3_2b = _ConvBNAct(384, 384, (3, 1), padding=(1, 0))
        self.branch3x3dbl_1 = nn.Sequential(_ConvBNAct(cin, 448, 1),
                                            _ConvBNAct(448, 384, 3, padding=1))
        self.branch3x3dbl_2a = _ConvBNAct(384, 384, (1, 3), padding=(0, 1))
        self.branch3x3dbl_2b = _ConvBNAct(384, 384, (3, 1), padding=(1, 0))
        self.pool = nn.AvgPool2D(3, stride=1, padding=1)
        self.branch_pool = _ConvBNAct(cin, 192, 1)

    def forward(self, x):
        b3 = self.branch3x3_1(x)
        b3 = concat([self.branch3x3_2a(b3), self.branch3x3_2b(b3)], axis=1)
        bd = self.branch3x3dbl_1(x)
        bd = concat([self.branch3x3dbl_2a(bd), self.branch3x3dbl_2b(bd)], axis=1)
        return concat([self.branch1x1(x), b3, bd, self.branch_pool(self.pool(x))], axis=1)


class InceptionV3(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.stem = nn.Sequential(
            _ConvBNAct(3, 32, 3, stride=2),
            _ConvBNAct(32, 32, 3),
            _ConvBNAct(32, 64, 3, padding=1),
            nn.MaxPool2D(3, stride=2),
            _ConvBNAct(64, 80, 1),
            _ConvBNAct(80, 192, 3),
            nn.MaxPool2D(3, stride=2),
        )
        self.blocks = nn.Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64), _InceptionA(288, 64),
            _InceptionB(288),
            _InceptionC(768, 128), _InceptionC(768, 160), _InceptionC(768, 160), _InceptionC(768, 192),
            _InceptionD(768),
            _InceptionE(1280), _InceptionE(2048),
        )
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = flatten(x, 1)
            x = self.fc(self.dropout(x))
        return x


def inception_v3(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access; load weights via set_state_dict")
    return InceptionV3(**kwargs)
