"""MobileNetV1 (parity: python/paddle/vision/models/mobilenetv1.py)."""

from __future__ import annotations

from ... import nn
from ...ops.manipulation import flatten


class _ConvBNRelu(nn.Layer):
    def __init__(self, cin, cout, kernel, stride=1, padding=0, groups=1):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, kernel, stride=stride, padding=padding,
                              groups=groups, bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class _DepthwiseSeparable(nn.Layer):
    def __init__(self, cin, cmid, cout, stride, scale):
        super().__init__()
        cin, cmid, cout = int(cin * scale), int(cmid * scale), int(cout * scale)
        self.dw = _ConvBNRelu(cin, cmid, 3, stride=stride, padding=1, groups=cmid)
        self.pw = _ConvBNRelu(cmid, cout, 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv1 = _ConvBNRelu(3, int(32 * scale), 3, stride=2, padding=1)
        cfg = [
            (32, 32, 64, 1), (64, 64, 128, 2), (128, 128, 128, 1), (128, 128, 256, 2),
            (256, 256, 256, 1), (256, 256, 512, 2),
            (512, 512, 512, 1), (512, 512, 512, 1), (512, 512, 512, 1),
            (512, 512, 512, 1), (512, 512, 512, 1),
            (512, 512, 1024, 2), (1024, 1024, 1024, 1),
        ]
        self.blocks = nn.Sequential(*[
            _DepthwiseSeparable(cin, cmid, cout, stride, scale)
            for cin, cmid, cout, stride in cfg
        ])
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = flatten(x, 1)
            x = self.fc(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access; load weights via set_state_dict")
    return MobileNetV1(scale=scale, **kwargs)
