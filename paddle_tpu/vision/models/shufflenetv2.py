"""ShuffleNetV2 (parity: python/paddle/vision/models/shufflenetv2.py)."""

from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat, flatten, reshape, transpose


def channel_shuffle(x, groups: int):
    n, c, h, w = x.shape
    x = reshape(x, [n, groups, c // groups, h, w])
    x = transpose(x, [0, 2, 1, 3, 4])
    return reshape(x, [n, c, h, w])


def _act_layer(name):
    return {"relu": nn.ReLU, "swish": nn.Swish}[name]


class _ConvBNAct(nn.Layer):
    def __init__(self, cin, cout, kernel, stride=1, groups=1, act="relu"):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, kernel, stride=stride,
                              padding=(kernel - 1) // 2, groups=groups, bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)
        self.act = _act_layer(act)() if act else None

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act is not None else x


class _InvertedResidual(nn.Layer):
    """Stride-1 unit: split channels, transform one branch, shuffle."""

    def __init__(self, channels, act="relu"):
        super().__init__()
        c = channels // 2
        self.branch = nn.Sequential(
            _ConvBNAct(c, c, 1, act=act),
            _ConvBNAct(c, c, 3, groups=c, act=None),
            _ConvBNAct(c, c, 1, act=act),
        )

    def forward(self, x):
        c = x.shape[1] // 2
        x1 = x[:, :c]
        x2 = x[:, c:]
        out = concat([x1, self.branch(x2)], axis=1)
        return channel_shuffle(out, 2)


class _InvertedResidualDS(nn.Layer):
    """Stride-2 unit: both branches downsample; channels double."""

    def __init__(self, cin, cout, act="relu"):
        super().__init__()
        c = cout // 2
        self.branch1 = nn.Sequential(
            _ConvBNAct(cin, cin, 3, stride=2, groups=cin, act=None),
            _ConvBNAct(cin, c, 1, act=act),
        )
        self.branch2 = nn.Sequential(
            _ConvBNAct(cin, c, 1, act=act),
            _ConvBNAct(c, c, 3, stride=2, groups=c, act=None),
            _ConvBNAct(c, c, 1, act=act),
        )

    def forward(self, x):
        out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


_STAGE_OUT = {
    0.25: [24, 24, 48, 96, 512], 0.33: [24, 32, 64, 128, 512],
    0.5: [24, 48, 96, 192, 1024], 1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024], 2.0: [24, 244, 488, 976, 2048],
}
_STAGE_REPEATS = [4, 8, 4]


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        outs = _STAGE_OUT[scale]

        self.conv1 = _ConvBNAct(3, outs[0], 3, stride=2, act=act)
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        cin = outs[0]
        for i, reps in enumerate(_STAGE_REPEATS):
            cout = outs[i + 1]
            stages.append(_InvertedResidualDS(cin, cout, act=act))
            for _ in range(reps - 1):
                stages.append(_InvertedResidual(cout, act=act))
            cin = cout
        self.stages = nn.Sequential(*stages)
        self.conv_last = _ConvBNAct(cin, outs[-1], 1, act=act)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(outs[-1], num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        x = self.conv_last(self.stages(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = flatten(x, 1)
            x = self.fc(x)
        return x


def _shufflenet(scale, pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access; load weights via set_state_dict")
    return ShuffleNetV2(scale=scale, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet(0.25, pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet(0.33, pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet(0.5, pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet(1.0, pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet(1.5, pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet(2.0, pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shufflenet(1.0, pretrained, act="swish", **kwargs)
