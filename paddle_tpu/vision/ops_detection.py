"""Detection op family (ops.yaml entries: yolo_box, yolo_loss, prior_box,
matrix_nms, multiclass_nms3, box_clip, bipartite_match, roi_pool,
psroi_pool, generate_proposals, distribute_fpn_proposals).

TPU design: every op is pure jnp over batched boxes — sorts/cumsums and
masked selects instead of data-dependent loops, so the hot ones compile
under jit; host-side greedy fallbacks only where the reference's
algorithm is inherently sequential (bipartite match).
Reference kernels: paddle/phi/kernels/ yolo_box_kernel, prior_box,
matrix_nms, multiclass_nms3, roi_pool, psroi_pool, generate_proposals.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops.dispatch import apply_op, ensure_tensor

__all__ = [
    "yolo_box", "yolo_loss", "prior_box", "box_clip", "bipartite_match",
    "matrix_nms", "multiclass_nms", "psroi_pool",
    "distribute_fpn_proposals", "generate_proposals",
]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def yolo_box(x, img_size, anchors: Sequence[int], class_num: int,
             conf_thresh: float, downsample_ratio: int, clip_bbox: bool = True,
             scale_x_y: float = 1.0, iou_aware: bool = False,
             iou_aware_factor: float = 0.5, name=None):
    """Decode YOLO detection head output to boxes+scores (parity:
    phi yolo_box_kernel). x: [N, C, H, W] with C = na*(5+class_num)."""
    x, img_size = ensure_tensor(x), ensure_tensor(img_size)
    na = len(anchors) // 2
    anc = np.asarray(anchors, np.float32).reshape(na, 2)

    def _f(feat, imgs):
        N, C, H, W = feat.shape
        if iou_aware:
            # layout: first na channels are IoU predictions (phi yolo_box
            # iou-aware path); conf = conf^(1-f) * sigmoid(iou)^f
            iou_pred = jax.nn.sigmoid(feat[:, :na].reshape(N, na, H, W))
            feat = feat[:, na:]
        feat = feat.reshape(N, na, 5 + class_num, H, W)
        gx = jax.lax.broadcasted_iota(jnp.float32, (H, W), 1)
        gy = jax.lax.broadcasted_iota(jnp.float32, (H, W), 0)
        sig = jax.nn.sigmoid
        bx = (sig(feat[:, :, 0]) * scale_x_y - 0.5 * (scale_x_y - 1) + gx) / W
        by = (sig(feat[:, :, 1]) * scale_x_y - 0.5 * (scale_x_y - 1) + gy) / H
        in_w, in_h = W * downsample_ratio, H * downsample_ratio
        bw = jnp.exp(feat[:, :, 2]) * anc[None, :, 0, None, None] / in_w
        bh = jnp.exp(feat[:, :, 3]) * anc[None, :, 1, None, None] / in_h
        conf = sig(feat[:, :, 4])
        if iou_aware:
            conf = conf ** (1.0 - iou_aware_factor) * iou_pred ** iou_aware_factor
        cls = sig(feat[:, :, 5:])
        score = conf[:, :, None] * cls
        imw = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        imh = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        x0 = (bx - bw / 2) * imw
        y0 = (by - bh / 2) * imh
        x1 = (bx + bw / 2) * imw
        y1 = (by + bh / 2) * imh
        if clip_bbox:
            x0 = jnp.clip(x0, 0, imw - 1)
            y0 = jnp.clip(y0, 0, imh - 1)
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
        boxes = jnp.stack([x0, y0, x1, y1], axis=-1).reshape(N, na * H * W, 4)
        scores = jnp.moveaxis(score, 2, -1).reshape(N, na * H * W, class_num)
        keep = (conf.reshape(N, na * H * W, 1) >= conf_thresh).astype(boxes.dtype)
        return boxes * keep, scores * keep

    boxes, scores = apply_op("yolo_box", _f, x, img_size, nouts=2)
    return boxes, scores


def yolo_loss(x, gt_box, gt_label, anchors: Sequence[int],
              anchor_mask: Sequence[int], class_num: int, ignore_thresh: float,
              downsample_ratio: int, gt_score=None, use_label_smooth: bool = True,
              scale_x_y: float = 1.0, name=None) -> Tensor:
    """YOLOv3 training loss (parity: phi yolo_loss_kernel): coordinate MSE
    + objectness/class BCE against anchor-matched targets. Negative cells
    whose predicted box overlaps any gt above ``ignore_thresh`` are
    excluded from the objectness loss; ``gt_score`` (mixup) weights the
    positive terms."""
    if scale_x_y != 1.0:
        raise NotImplementedError(
            "yolo_loss scale_x_y != 1.0 (grid-sensitive decode) is not "
            "implemented; yolo_box supports it for inference decode")
    x, gt_box, gt_label = ensure_tensor(x), ensure_tensor(gt_box), ensure_tensor(gt_label)
    gscore = ensure_tensor(gt_score) if gt_score is not None else None
    na = len(anchor_mask)
    anc = np.asarray(anchors, np.float32).reshape(-1, 2)
    mask_anc = anc[np.asarray(anchor_mask)]

    def _f(feat, gboxes, glabels, *rest):
        gs = rest[0] if rest else None
        N, C, H, W = feat.shape
        feat = feat.reshape(N, na, 5 + class_num, H, W)
        in_w = W * downsample_ratio
        in_h = H * downsample_ratio
        B = gboxes.shape[1]

        # target assignment: each gt lands in its center cell with the
        # best-matching masked anchor (by wh IoU)
        gx = gboxes[:, :, 0] * W      # [N, B]
        gy = gboxes[:, :, 1] * H
        gw = gboxes[:, :, 2] * in_w
        gh = gboxes[:, :, 3] * in_h
        valid = (gboxes[:, :, 2] > 0) & (gboxes[:, :, 3] > 0)

        inter = (jnp.minimum(gw[:, :, None], mask_anc[None, None, :, 0])
                 * jnp.minimum(gh[:, :, None], mask_anc[None, None, :, 1]))
        union = gw[:, :, None] * gh[:, :, None] + (mask_anc[:, 0] * mask_anc[:, 1])[None, None] - inter
        best_a = jnp.argmax(inter / jnp.maximum(union, 1e-9), axis=-1)  # [N, B]

        ci = jnp.clip(gx.astype(jnp.int32), 0, W - 1)
        cj = jnp.clip(gy.astype(jnp.int32), 0, H - 1)

        tx = gx - ci
        ty = gy - cj
        tw = jnp.log(jnp.maximum(gw / jnp.maximum(mask_anc[best_a][..., 0], 1e-9), 1e-9))
        th = jnp.log(jnp.maximum(gh / jnp.maximum(mask_anc[best_a][..., 1], 1e-9), 1e-9))
        tscale = 2.0 - gboxes[:, :, 2] * gboxes[:, :, 3]

        sig = jax.nn.sigmoid
        px = sig(feat[:, :, 0])
        py = sig(feat[:, :, 1])
        pobj = feat[:, :, 4]

        bidx = jnp.arange(N)[:, None].repeat(B, 1)
        sel = (bidx, best_a, cj, ci)
        vf = valid.astype(feat.dtype)
        if gs is not None:
            vf = vf * gs  # mixup weighting of positive terms
        loss_xy = (((px[sel] - tx) ** 2 + (py[sel] - ty) ** 2) * tscale * vf).sum(-1)
        loss_wh = (((feat[:, :, 2][sel] - tw) ** 2 + (feat[:, :, 3][sel] - th) ** 2)
                   * tscale * vf).sum(-1)

        # objectness: positives at assigned cells; negatives elsewhere,
        # except cells whose decoded box overlaps a gt above ignore_thresh
        obj_t = jnp.zeros((N, na, H, W), feat.dtype)
        obj_t = obj_t.at[sel].max(valid.astype(feat.dtype))
        # decoded predicted boxes (normalized, cell units)
        gxg = jax.lax.broadcasted_iota(jnp.float32, (H, W), 1)
        gyg = jax.lax.broadcasted_iota(jnp.float32, (H, W), 0)
        pbx = (px + gxg) / W
        pby = (py + gyg) / H
        pbw = jnp.exp(jnp.clip(feat[:, :, 2], -10, 10)) * mask_anc[None, :, 0, None, None] / in_w
        pbh = jnp.exp(jnp.clip(feat[:, :, 3], -10, 10)) * mask_anc[None, :, 1, None, None] / in_h
        # IoU of each predicted box with each gt (normalized coords)
        gx0 = (gboxes[:, :, 0] - gboxes[:, :, 2] / 2)[:, None, None, None, :]
        gy0 = (gboxes[:, :, 1] - gboxes[:, :, 3] / 2)[:, None, None, None, :]
        gx1 = (gboxes[:, :, 0] + gboxes[:, :, 2] / 2)[:, None, None, None, :]
        gy1 = (gboxes[:, :, 1] + gboxes[:, :, 3] / 2)[:, None, None, None, :]
        px0 = (pbx - pbw / 2)[..., None]
        py0 = (pby - pbh / 2)[..., None]
        px1 = (pbx + pbw / 2)[..., None]
        py1 = (pby + pbh / 2)[..., None]
        iw = jnp.maximum(jnp.minimum(px1, gx1) - jnp.maximum(px0, gx0), 0)
        ih = jnp.maximum(jnp.minimum(py1, gy1) - jnp.maximum(py0, gy0), 0)
        inter_p = iw * ih
        union_p = (px1 - px0) * (py1 - py0) + (gx1 - gx0) * (gy1 - gy0) - inter_p
        best_iou = jnp.where(valid[:, None, None, None, :], inter_p
                             / jnp.maximum(union_p, 1e-9), 0.0).max(-1)
        ignore = (best_iou > ignore_thresh) & (obj_t == 0)
        w_obj = jnp.where(ignore, 0.0, 1.0)
        bce = (jax.nn.softplus(pobj) - pobj * obj_t) * w_obj
        loss_obj = bce.sum((1, 2, 3))

        # classification at positive cells
        delta = 1.0 / class_num if use_label_smooth else 0.0
        onehot = jax.nn.one_hot(glabels, class_num, dtype=feat.dtype)
        onehot = onehot * (1 - delta) + delta / class_num
        pcls = jnp.moveaxis(feat[:, :, 5:], 2, -1)  # [N, na, H, W, cls]
        logits = pcls[sel]                           # [N, B, cls]
        cls_bce = jax.nn.softplus(logits) - logits * onehot
        loss_cls = (cls_bce.sum(-1) * vf).sum(-1)

        return loss_xy + loss_wh + loss_obj + loss_cls

    args = (x, gt_box, gt_label) + ((gscore,) if gscore is not None else ())
    return apply_op("yolo_loss", _f, *args)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip: bool = False,
              clip: bool = False, steps=(0.0, 0.0), offset: float = 0.5,
              min_max_aspect_ratios_order: bool = False, name=None):
    """SSD prior boxes (parity: phi prior_box_kernel)."""
    if min_max_aspect_ratios_order:
        raise NotImplementedError(
            "prior_box min_max_aspect_ratios_order=True (caffe box "
            "ordering) not implemented")
    input, image = ensure_tensor(input), ensure_tensor(image)
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))

    H, W = int(input.shape[2]), int(input.shape[3])
    img_h, img_w = int(image.shape[2]), int(image.shape[3])
    step_w = steps[0] or img_w / W
    step_h = steps[1] or img_h / H

    boxes = []
    for ms in min_sizes:
        ms = float(ms)
        for ar in ars:
            boxes.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
    if max_sizes:
        for ms, mx in zip(min_sizes, max_sizes):
            boxes.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
    nb = len(boxes)
    wh = np.asarray(boxes, np.float32)  # [nb, 2]

    cx = (np.arange(W) + offset) * step_w
    cy = (np.arange(H) + offset) * step_h
    CX, CY = np.meshgrid(cx, cy)
    out = np.zeros((H, W, nb, 4), np.float32)
    out[..., 0] = (CX[:, :, None] - wh[None, None, :, 0] / 2) / img_w
    out[..., 1] = (CY[:, :, None] - wh[None, None, :, 1] / 2) / img_h
    out[..., 2] = (CX[:, :, None] + wh[None, None, :, 0] / 2) / img_w
    out[..., 3] = (CY[:, :, None] + wh[None, None, :, 1] / 2) / img_h
    if clip:
        out = np.clip(out, 0, 1)
    var = np.broadcast_to(np.asarray(variance, np.float32), out.shape).copy()
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(var))


def box_clip(input, im_info, name=None) -> Tensor:
    """Clip boxes to image bounds (parity: phi box_clip_kernel).
    im_info rows: [h, w, scale]."""
    input, im_info = ensure_tensor(input), ensure_tensor(im_info)

    def _f(boxes, info):
        h = info[..., 0:1] / info[..., 2:3] - 1
        w = info[..., 1:2] / info[..., 2:3] - 1
        while h.ndim < boxes.ndim:
            h = h[..., None, :]
            w = w[..., None, :]
        x0 = jnp.clip(boxes[..., 0::2], 0, w)
        y0 = jnp.clip(boxes[..., 1::2], 0, h)
        out = jnp.stack([x0[..., 0], y0[..., 0], x0[..., 1], y0[..., 1]], axis=-1)
        return out

    return apply_op("box_clip", _f, input, im_info)


from .ops import _iou_matrix  # shared box helper (defined before the
# tail wildcard import in ops.py, so this back-import is safe)


def bipartite_match(dist_mat, match_type: Optional[str] = None,
                    dist_threshold: Optional[float] = None, name=None):
    """Greedy bipartite matching (parity: phi bipartite_match_kernel).
    Host-side sequential greedy like the reference CPU kernel."""
    d = np.asarray(_arr(dist_mat))
    if d.ndim == 2:
        d = d[None]
    B, R, C = d.shape
    indices = np.full((B, C), -1, np.int64)
    dists = np.zeros((B, C), np.float32)
    for b in range(B):
        m = d[b].copy()
        # global greedy: repeatedly take the largest remaining pair
        for _ in range(min(R, C)):
            i, j = np.unravel_index(np.argmax(m), m.shape)
            if m[i, j] <= 0:
                break
            indices[b, j] = i
            dists[b, j] = m[i, j]
            m[i, :] = -1
            m[:, j] = -1
        if match_type == "per_prediction" and dist_threshold is not None:
            for j in range(C):
                if indices[b, j] == -1:
                    i = int(np.argmax(d[b][:, j]))
                    if d[b][i, j] >= dist_threshold:
                        indices[b, j] = i
                        dists[b, j] = d[b][i, j]
    return Tensor(jnp.asarray(indices)), Tensor(jnp.asarray(dists))


def matrix_nms(bboxes, scores, score_threshold: float, post_threshold: float,
               nms_top_k: int, keep_top_k: int, use_gaussian: bool = False,
               gaussian_sigma: float = 2.0, background_label: int = 0,
               normalized: bool = True, return_index: bool = False, name=None):
    """Matrix NMS (parity: phi matrix_nms_kernel): soft suppression via the
    pairwise IoU matrix — sort, compute decay, rescore. Fully vectorized
    (SOLOv2's TPU-friendly alternative to sequential NMS)."""
    if not normalized:
        raise NotImplementedError(
            "matrix_nms normalized=False (+1 pixel box widths) not "
            "implemented; pass normalized coordinates")
    bb = _arr(bboxes)
    sc = _arr(scores)
    if bb.ndim == 2:
        bb, sc = bb[None], sc[None]
    N, M, _ = bb.shape
    C = sc.shape[1]
    outs, inds = [], []
    for n in range(N):
        rows = []
        idxs = []
        for c in range(C):
            if c == background_label:
                continue
            s = sc[n, c]
            k = min(nms_top_k, M) if nms_top_k > 0 else M
            order = jnp.argsort(-s)[:k]
            s_sorted = s[order]
            valid = s_sorted > score_threshold
            b_sorted = bb[n][order]
            iou = jnp.triu(_iou_matrix(b_sorted, b_sorted), k=1)
            # comp[i] = box i's own max IoU with better-ranked boxes; the
            # SOLOv2 decay divides it out row-wise (matrix_nms_kernel.cc)
            comp = iou.max(axis=0)
            if use_gaussian:
                decay = jnp.exp(-(iou ** 2 - comp[:, None] ** 2) / gaussian_sigma).min(0)
            else:
                decay = ((1 - iou) / jnp.maximum(1 - comp[:, None], 1e-9)).min(0)
            new_s = s_sorted * decay * valid
            keep = new_s > post_threshold
            rows.append(jnp.concatenate([
                jnp.full((k, 1), c, jnp.float32), new_s[:, None].astype(jnp.float32),
                b_sorted.astype(jnp.float32)], axis=1) * keep[:, None])
            idxs.append(order)
        allr = np.asarray(jnp.concatenate(rows, 0))
        alli = np.asarray(jnp.concatenate(idxs, 0))
        kept = allr[:, 1] > post_threshold  # drop suppressed (zeroed) rows
        allr, alli = allr[kept], alli[kept]
        order = np.argsort(-allr[:, 1])
        if keep_top_k > 0:
            order = order[:keep_top_k]
        outs.append(jnp.asarray(allr[order]))
        inds.append(jnp.asarray(alli[order]))
    out = Tensor(outs[0] if N == 1 else jnp.stack(outs))
    rois_num = Tensor(jnp.asarray([int(o.shape[0]) for o in outs], jnp.int32))
    if return_index:
        return out, Tensor(inds[0] if N == 1 else jnp.stack(inds)), rois_num
    return out, rois_num


def multiclass_nms(bboxes, scores, score_threshold: float = 0.05,
                   nms_top_k: int = 400, keep_top_k: int = 100,
                   nms_threshold: float = 0.45, normalized: bool = True,
                   nms_eta: float = 1.0, background_label: int = -1,
                   return_index: bool = False, return_rois_num: bool = True,
                   rois_num=None, name=None):
    """Hard multiclass NMS (parity: ops.yaml multiclass_nms3). Greedy
    per-class suppression on host (sequential by nature, like the
    reference CPU kernel)."""
    if not normalized or nms_eta != 1.0 or rois_num is not None:
        raise NotImplementedError(
            "multiclass_nms: normalized=False / adaptive nms_eta / "
            "rois_num batching are not implemented — raise instead of "
            "silently ignoring them")
    bb = np.asarray(_arr(bboxes))
    sc = np.asarray(_arr(scores))
    if bb.ndim == 2:
        bb, sc = bb[None], sc[None]
    N, M, _ = bb.shape
    C = sc.shape[1]
    all_out, all_idx, nums = [], [], []
    for n in range(N):
        dets = []
        for c in range(C):
            if c == background_label:
                continue
            s = sc[n, c]
            order = np.argsort(-s)[: nms_top_k if nms_top_k > 0 else M]
            order = order[s[order] > score_threshold]
            keep = []
            while order.size:
                i = order[0]
                keep.append(i)
                if order.size == 1:
                    break
                rest = order[1:]
                iou = np.asarray(_iou_matrix(jnp.asarray(bb[n][i][None]),
                                             jnp.asarray(bb[n][rest])))[0]
                order = rest[iou <= nms_threshold]
            for i in keep:
                dets.append((c, s[i], *bb[n][i], i))
        dets.sort(key=lambda r: -r[1])
        if keep_top_k > 0:
            dets = dets[:keep_top_k]
        nums.append(len(dets))
        for d in dets:
            all_out.append(d[:6])
            all_idx.append(d[6] + n * M)
    out = Tensor(jnp.asarray(np.asarray(all_out, np.float32).reshape(-1, 6)))
    idx = Tensor(jnp.asarray(np.asarray(all_idx, np.int64).reshape(-1, 1)))
    nums_t = Tensor(jnp.asarray(np.asarray(nums, np.int32)))
    if return_index:
        return (out, idx, nums_t) if return_rois_num else (out, idx)
    return (out, nums_t) if return_rois_num else out


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0, name=None) -> Tensor:
    """Position-sensitive RoI average pooling (parity: phi psroi_pool).
    Channels are grouped oh*ow position-sensitive maps."""
    x = ensure_tensor(x)
    boxes_t = boxes if isinstance(boxes, Tensor) else Tensor(_arr(boxes))
    bn = np.asarray(_arr(boxes_num)).astype(np.int64)
    oh, ow = (output_size, output_size) if isinstance(output_size, int) else tuple(output_size)
    batch_idx = np.repeat(np.arange(len(bn)), bn)

    def _f(feat, rois):
        N, C, H, W = feat.shape
        co = C // (oh * ow)
        r = rois * spatial_scale

        def pool_one(bi, box):
            x0, y0, x1, y1 = box
            h = jnp.maximum(y1 - y0, 0.1)
            w = jnp.maximum(x1 - x0, 0.1)
            bin_h = h / oh
            bin_w = w / ow
            img = feat[bi].reshape(co, oh, ow, H, W)
            ys = y0 + jnp.arange(oh) * bin_h
            xs = x0 + jnp.arange(ow) * bin_w
            yy = jnp.arange(H)[None, :]
            xx = jnp.arange(W)[None, :]
            ymask = (yy >= jnp.floor(ys)[:, None]) & (yy < jnp.ceil(ys + bin_h)[:, None])
            xmask = (xx >= jnp.floor(xs)[:, None]) & (xx < jnp.ceil(xs + bin_w)[:, None])
            m = ymask[None, :, None, :, None] & xmask[None, None, :, None, :]
            cnt = jnp.maximum(m.sum((-1, -2)), 1)
            # position-sensitive: bin (i,j) reads channel group (i,j)
            sel = jnp.where(m, jnp.moveaxis(img, 0, 0), 0.0)
            return sel.sum((-1, -2)) / cnt

        return jax.vmap(pool_one)(jnp.asarray(batch_idx), r)

    return apply_op("psroi_pool", _f, x, boxes_t)


def distribute_fpn_proposals(fpn_rois, min_level: int, max_level: int,
                             refer_level: int, refer_scale: int,
                             pixel_offset: bool = False, rois_num=None, name=None):
    """Assign RoIs to FPN levels by scale (parity: phi
    distribute_fpn_proposals_kernel)."""
    rois = np.asarray(_arr(fpn_rois))
    w = rois[:, 2] - rois[:, 0] + (0 if not pixel_offset else 1)
    h = rois[:, 3] - rois[:, 1] + (0 if not pixel_offset else 1)
    scale = np.sqrt(np.maximum(w * h, 1e-12))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    outs, idxs = [], []
    order = []
    for l in range(min_level, max_level + 1):
        sel = np.where(lvl == l)[0]
        outs.append(Tensor(jnp.asarray(rois[sel])))
        idxs.append(sel)
        order.append(sel)
    restore = np.argsort(np.concatenate(order)) if order else np.zeros(0, np.int64)
    return outs, Tensor(jnp.asarray(restore.astype(np.int32).reshape(-1, 1)))


def generate_proposals(scores, bbox_deltas, im_shape, anchors, variances,
                       pre_nms_top_n: int = 6000, post_nms_top_n: int = 1000,
                       nms_thresh: float = 0.5, min_size: float = 0.1,
                       eta: float = 1.0, pixel_offset: bool = False,
                       return_rois_num: bool = False, name=None):
    """RPN proposal generation (parity: phi generate_proposals_v2): decode
    anchors with deltas, clip, filter small, NMS, top-k."""
    if eta != 1.0 or pixel_offset:
        raise NotImplementedError(
            "generate_proposals: adaptive eta / pixel_offset box widths "
            "are not implemented — raise instead of silently ignoring")
    sc = np.asarray(_arr(scores))       # [N, A, H, W]
    bd = np.asarray(_arr(bbox_deltas))  # [N, 4A, H, W]
    ims = np.asarray(_arr(im_shape))    # [N, 2]
    anc = np.asarray(_arr(anchors)).reshape(-1, 4)
    var = np.asarray(_arr(variances)).reshape(-1, 4)
    N = sc.shape[0]
    A = anc.shape[0] // (sc.shape[2] * sc.shape[3]) if anc.ndim == 2 else sc.shape[1]

    all_rois, all_scores, all_nums = [], [], []
    for n in range(N):
        s = sc[n].transpose(1, 2, 0).reshape(-1)
        d = bd[n].reshape(sc.shape[1], 4, sc.shape[2], sc.shape[3]).transpose(2, 3, 0, 1).reshape(-1, 4)
        aw = anc[:, 2] - anc[:, 0]
        ah = anc[:, 3] - anc[:, 1]
        acx = anc[:, 0] + aw / 2
        acy = anc[:, 1] + ah / 2
        dx, dy, dw, dh = (d * var).T
        cx = dx * aw + acx
        cy = dy * ah + acy
        w = np.exp(np.minimum(dw, 10)) * aw
        h = np.exp(np.minimum(dh, 10)) * ah
        boxes = np.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], 1)
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, ims[n, 1] - 1)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, ims[n, 0] - 1)
        keep = ((boxes[:, 2] - boxes[:, 0] >= min_size)
                & (boxes[:, 3] - boxes[:, 1] >= min_size))
        boxes, s = boxes[keep], s[keep]
        order = np.argsort(-s)[:pre_nms_top_n]
        boxes, s = boxes[order], s[order]
        keep_idx = []
        order = np.arange(len(s))
        while order.size and len(keep_idx) < post_nms_top_n:
            i = order[0]
            keep_idx.append(i)
            if order.size == 1:
                break
            rest = order[1:]
            iou = np.asarray(_iou_matrix(jnp.asarray(boxes[i][None]),
                                         jnp.asarray(boxes[rest])))[0]
            order = rest[iou <= nms_thresh]
        all_rois.append(boxes[keep_idx])
        all_scores.append(s[keep_idx])
        all_nums.append(len(keep_idx))
    rois = Tensor(jnp.asarray(np.concatenate(all_rois, 0).astype(np.float32)))
    nums = Tensor(jnp.asarray(np.asarray(all_nums, np.int32)))
    scores_out = Tensor(jnp.asarray(
        (np.concatenate(all_scores, 0).astype(np.float32).reshape(-1, 1))
        if all_scores else np.zeros((0, 1), np.float32)))
    if return_rois_num:
        return rois, scores_out, nums
    return rois, scores_out
