"""Transforms (parity: python/paddle/vision/transforms/ — numpy-backed
subset: Compose, Normalize, Resize, ToTensor, flips, crops)."""

from __future__ import annotations

import numbers
from typing import Sequence

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor:
    """HWC uint8 -> CHW float32 [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img)
        if arr.dtype == np.uint8:
            arr = arr.astype(np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[None]
        elif arr.ndim == 3 and self.data_format == "CHW" and arr.shape[-1] in (1, 3, 4):
            arr = arr.transpose(2, 0, 1)
        return arr.astype(np.float32)


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        return (arr - self.mean.reshape(shape)) / self.std.reshape(shape)


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)

    def __call__(self, img):
        import jax

        arr = np.asarray(img, np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if chw:
            out_shape = (arr.shape[0],) + self.size
        else:
            out_shape = self.size + ((arr.shape[-1],) if arr.ndim == 3 else ())
        return np.asarray(jax.image.resize(arr, out_shape, method="bilinear"))


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.ascontiguousarray(np.flip(img, axis=-1))
        return img


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.ascontiguousarray(np.flip(img, axis=-2))
        return img


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[-2], arr.shape[-1]
        th, tw = self.size
        i = (h - th) // 2
        j = (w - tw) // 2
        return arr[..., i:i + th, j:j + tw]


class RandomCrop:
    def __init__(self, size, padding=None):
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding
            pads = [(0, 0)] * (arr.ndim - 2) + [(p, p), (p, p)]
            arr = np.pad(arr, pads)
        h, w = arr.shape[-2], arr.shape[-1]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return arr[..., i:i + th, j:j + tw]


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW"):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)
