"""Vision datasets (parity: python/paddle/vision/datasets/).

Real dataset downloads need network; in this zero-egress environment the
loaders read local files when present (MNIST idx / cifar pickle formats,
same file formats as the reference) and otherwise raise with instructions.
``FakeData`` generates synthetic samples for pipelines and benchmarks
(reference analogue: paddle.vision datasets used in tests with small
slices).
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from typing import Callable, Optional

import numpy as np

from ..io.dataset import Dataset


class FakeData(Dataset):
    """Synthetic dataset with a fixed seed (deterministic)."""

    def __init__(self, size=1000, image_shape=(3, 224, 224), num_classes=10, transform=None,
                 dtype="float32"):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.dtype = dtype
        self._rng = np.random.RandomState(42)
        self._labels = self._rng.randint(0, num_classes, size)

    def __getitem__(self, idx):
        rng = np.random.RandomState(idx)
        img = rng.standard_normal(self.image_shape).astype(self.dtype)
        label = np.int64(self._labels[idx])
        if self.transform:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return self.size


class MNIST(Dataset):
    """Reads the standard idx-ubyte files (same format as reference's
    python/paddle/vision/datasets/mnist.py expects)."""

    def __init__(self, image_path=None, label_path=None, mode="train", transform=None,
                 download=False, backend=None, root=None):
        self.transform = transform
        root = root or os.environ.get("PADDLE_TPU_DATA_HOME", os.path.expanduser("~/.cache/paddle_tpu/mnist"))
        prefix = "train" if mode == "train" else "t10k"
        image_path = image_path or os.path.join(root, f"{prefix}-images-idx3-ubyte.gz")
        label_path = label_path or os.path.join(root, f"{prefix}-labels-idx1-ubyte.gz")
        if not (os.path.exists(image_path) and os.path.exists(label_path)):
            raise FileNotFoundError(
                f"MNIST files not found under {root}; place idx-ubyte(.gz) files there "
                "(no network access in this environment), or use vision.datasets.FakeData")
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")

    def _read_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, 1, rows, cols)
        return data.astype(np.float32) / 255.0

    def _read_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            return np.frombuffer(f.read(), dtype=np.uint8).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


FashionMNIST = MNIST


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None, download=False, backend=None):
        self.transform = transform
        data_file = data_file or os.path.join(
            os.environ.get("PADDLE_TPU_DATA_HOME", os.path.expanduser("~/.cache/paddle_tpu")),
            "cifar-10-python.tar.gz")
        if not os.path.exists(data_file):
            raise FileNotFoundError(
                f"{data_file} not found (no network access); use vision.datasets.FakeData")
        names = [f"data_batch_{i}" for i in range(1, 6)] if mode == "train" else ["test_batch"]
        xs, ys = [], []
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                if any(m.name.endswith(n) for n in names):
                    d = pickle.load(tf.extractfile(m), encoding="bytes")
                    xs.append(d[b"data"])
                    ys.extend(d[b"labels"])
        self.images = np.concatenate(xs).reshape(-1, 3, 32, 32).astype(np.float32) / 255.0
        self.labels = np.asarray(ys, np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    pass
