"""Vision detection ops (parity: python/paddle/vision/ops.py — nms,
roi_align, roi_pool, box_coder, DeformConv2D surface).

TPU design notes: NMS's data-dependent loop is expressed as a fixed-length
lax.scan over score-sorted boxes with a suppression mask (compilable,
no dynamic shapes); RoIAlign is gather + bilinear interpolation, which XLA
lowers to vectorized gathers — no custom CUDA kernel needed.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops.dispatch import apply_op

__all__ = ["nms", "roi_align", "roi_pool", "box_coder", "box_area", "box_iou",
           # detection family (ops_detection.py, re-exported below)
           "yolo_box", "yolo_loss", "prior_box", "box_clip",
           "bipartite_match", "matrix_nms", "multiclass_nms", "psroi_pool",
           "distribute_fpn_proposals", "generate_proposals"]


def _iou_matrix(a, b=None):
    # pairwise IoU [Na, Nb]; b defaults to a (self-IoU for NMS)
    if b is None:
        b = a
    area_a = jnp.maximum(a[:, 2] - a[:, 0], 0) * jnp.maximum(a[:, 3] - a[:, 1], 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0], 0) * jnp.maximum(b[:, 3] - b[:, 1], 0)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def nms(boxes: Tensor, iou_threshold: float = 0.3, scores: Optional[Tensor] = None,
        category_idxs: Optional[Tensor] = None, categories=None, top_k: Optional[int] = None):
    """Greedy hard NMS returning kept indices, score-descending (parity:
    paddle.vision.ops.nms). Category-aware when category_idxs given."""
    n = int(boxes.shape[0])

    def fn(*arrays):
        b = arrays[0]
        s = arrays[1] if scores is not None else jnp.arange(n, 0, -1, dtype=jnp.float32)
        order = jnp.argsort(-s)
        b_sorted = b[order]
        iou = _iou_matrix(b_sorted)
        if category_idxs is not None:
            cats = arrays[2] if scores is not None else arrays[1]
            cs = cats[order]
            same_cat = cs[:, None] == cs[None, :]
            iou = jnp.where(same_cat, iou, 0.0)

        def step(keep, i):
            # suppressed if any earlier kept box overlaps > threshold
            sup = jnp.any((iou[i] > iou_threshold) & keep & (jnp.arange(n) < i))
            keep = keep.at[i].set(~sup)
            return keep, ~sup

        keep0 = jnp.zeros(n, bool).at[0].set(True)
        keep, _ = jax.lax.scan(step, keep0, jnp.arange(1, n))
        kept_sorted_idx = jnp.nonzero(keep, size=n, fill_value=-1)[0]
        return order[kept_sorted_idx], keep.sum()

    args = [boxes]
    if scores is not None:
        args.append(scores)
    if category_idxs is not None:
        args.append(category_idxs)
    idx, count = apply_op("nms", fn, *args)
    k = int(count.numpy())
    out = Tensor(idx._data[:k])
    if top_k is not None:
        out = Tensor(out._data[:top_k])
    return out


def roi_align(x: Tensor, boxes: Tensor, boxes_num: Tensor, output_size,
              spatial_scale: float = 1.0, sampling_ratio: int = -1,
              aligned: bool = True, name=None) -> Tensor:
    """RoIAlign (parity: paddle.vision.ops.roi_align): bilinear-sampled
    pooling over boxes. x: [N, C, H, W]; boxes: [R, 4] across the batch
    with boxes_num per image."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    if sampling_ratio > 0:
        max_ratio = sampling_ratio
    else:
        # adaptive (reference: ceil(roi_size / pooled_size) per ROI). The
        # grid must be static for XLA, so allocate up to the max adaptive
        # ratio over the (concrete, eager) boxes and mask per-ROI; under a
        # tracer fall back to a fixed grid of 4.
        try:
            b_np = np.asarray(boxes._data)
            hmax = float(np.max((b_np[:, 3] - b_np[:, 1]) * spatial_scale)) / ph
            wmax = float(np.max((b_np[:, 2] - b_np[:, 0]) * spatial_scale)) / pw
            max_ratio = int(min(max(np.ceil(max(hmax, wmax, 1.0)), 1), 8))
        except Exception:
            max_ratio = 4
    ratio = max_ratio
    adaptive = sampling_ratio <= 0

    bn = jnp.asarray(boxes_num._data if isinstance(boxes_num, Tensor) else boxes_num)
    batch_idx = jnp.repeat(jnp.arange(bn.shape[0]), bn, total_repeat_length=int(boxes.shape[0]))

    def fn(x, rois):
        off = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - off
        y1 = rois[:, 1] * spatial_scale - off
        x2 = rois[:, 2] * spatial_scale - off
        y2 = rois[:, 3] * spatial_scale - off
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        H, W = x.shape[2], x.shape[3]

        def sample_one(img, rx1, ry1, rbw, rbh):
            # per-ROI adaptive sample count within the static [ratio] grid
            if adaptive:
                rat_h = jnp.clip(jnp.ceil(rbh / ph), 1, ratio)
                rat_w = jnp.clip(jnp.ceil(rbw / pw), 1, ratio)
            else:
                rat_h = rat_w = jnp.asarray(float(ratio))
            ks = jnp.arange(ratio, dtype=jnp.float32)
            valid_h = ks < rat_h            # [ratio]
            valid_w = ks < rat_w
            bys = (jnp.arange(ph)[:, None] + (ks[None, :] + 0.5) / rat_h) / ph
            bxs = (jnp.arange(pw)[:, None] + (ks[None, :] + 0.5) / rat_w) / pw
            ys = ry1 + bys * rbh            # [ph, ratio]
            xs = rx1 + bxs * rbw            # [pw, ratio]

            def bilinear(yy, xx):
                yy = jnp.clip(yy, 0, H - 1)
                xx = jnp.clip(xx, 0, W - 1)
                y0 = jnp.floor(yy).astype(jnp.int32)
                x0 = jnp.floor(xx).astype(jnp.int32)
                y1c = jnp.minimum(y0 + 1, H - 1)
                x1c = jnp.minimum(x0 + 1, W - 1)
                wy = yy - y0
                wx = xx - x0
                v00 = img[:, y0, :][:, :, x0]
                v01 = img[:, y0, :][:, :, x1c]
                v10 = img[:, y1c, :][:, :, x0]
                v11 = img[:, y1c, :][:, :, x1c]
                return (v00 * (1 - wy)[None, :, None] * (1 - wx)[None, None, :]
                        + v01 * (1 - wy)[None, :, None] * wx[None, None, :]
                        + v10 * wy[None, :, None] * (1 - wx)[None, None, :]
                        + v11 * wy[None, :, None] * wx[None, None, :])

            ys_flat = ys.reshape(-1)        # [ph*ratio]
            xs_flat = xs.reshape(-1)        # [pw*ratio]
            vals = bilinear(ys_flat, xs_flat)  # [C, ph*ratio, pw*ratio]
            C = vals.shape[0]
            vals = vals.reshape(C, ph, ratio, pw, ratio)
            mask = (valid_h[:, None] & valid_w[None, :]).astype(vals.dtype)  # [ratio, ratio]
            num = (vals * mask[None, None, :, None, :]).sum(axis=(2, 4))
            return num / (rat_h * rat_w)    # [C, ph, pw]

        imgs = x[batch_idx]                 # [R, C, H, W]
        return jax.vmap(sample_one)(imgs, x1, y1, rw, rh)

    return apply_op("roi_align", fn, x, boxes)


def roi_pool(x: Tensor, boxes: Tensor, boxes_num: Tensor, output_size,
             spatial_scale: float = 1.0, name=None) -> Tensor:
    """RoIPool (max pooling per bin; parity: paddle.vision.ops.roi_pool).
    Implemented via dense bin-membership masks (compilable, no dynamic
    shapes): bin value = max over pixels whose index falls in the bin."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    bn = jnp.asarray(boxes_num._data if isinstance(boxes_num, Tensor) else boxes_num)
    batch_idx = jnp.repeat(jnp.arange(bn.shape[0]), bn, total_repeat_length=int(boxes.shape[0]))

    def fn(x, rois):
        H, W = x.shape[2], x.shape[3]
        x1 = jnp.round(rois[:, 0] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(rois[:, 1] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(rois[:, 2] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(rois[:, 3] * spatial_scale).astype(jnp.int32)

        def pool_one(img, rx1, ry1, rx2, ry2):
            rw = jnp.maximum(rx2 - rx1 + 1, 1)
            rh = jnp.maximum(ry2 - ry1 + 1, 1)
            ys = jnp.arange(H)
            xs = jnp.arange(W)
            # bin index of each pixel, relative to the roi
            by = jnp.floor((ys - ry1).astype(jnp.float32) * ph / rh).astype(jnp.int32)
            bx = jnp.floor((xs - rx1).astype(jnp.float32) * pw / rw).astype(jnp.int32)
            in_y = (ys >= ry1) & (ys <= ry2)
            in_x = (xs >= rx1) & (xs <= rx2)
            ymask = (by[None, :] == jnp.arange(ph)[:, None]) & in_y[None, :]   # [ph, H]
            xmask = (bx[None, :] == jnp.arange(pw)[:, None]) & in_x[None, :]   # [pw, W]
            # max over H with ymask, then over W with xmask
            a = jnp.where(ymask[None, :, :, None], img[:, None, :, :], -jnp.inf).max(axis=2)  # [C, ph, W]
            b = jnp.where(xmask[None, None, :, :], a[:, :, None, :], -jnp.inf).max(axis=3)    # [C, ph, pw]
            return jnp.where(jnp.isfinite(b), b, 0.0)

        imgs = x[batch_idx]
        return jax.vmap(pool_one)(imgs, x1, y1, x2, y2)

    return apply_op("roi_pool", fn, x, boxes)


def box_area(boxes: Tensor) -> Tensor:
    def fn(b):
        return (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])

    return apply_op("box_area", fn, boxes)


def box_iou(boxes1: Tensor, boxes2: Tensor) -> Tensor:
    return apply_op("box_iou", _iou_matrix, boxes1, boxes2)


def box_coder(prior_box: Tensor, prior_box_var, target_box: Tensor,
              code_type: str = "encode_center_size", box_normalized: bool = True,
              axis: int = 0, name=None) -> Tensor:
    """Encode/decode boxes against priors (parity: paddle.vision.ops.box_coder)."""
    var = prior_box_var._data if isinstance(prior_box_var, Tensor) else jnp.asarray(prior_box_var, jnp.float32)

    def fn(prior, target):
        norm = 0.0 if box_normalized else 1.0
        pw = prior[:, 2] - prior[:, 0] + norm
        phh = prior[:, 3] - prior[:, 1] + norm
        pcx = prior[:, 0] + pw * 0.5
        pcy = prior[:, 1] + phh * 0.5
        if code_type == "encode_center_size":
            tw = target[:, 2] - target[:, 0] + norm
            th = target[:, 3] - target[:, 1] + norm
            tcx = target[:, 0] + tw * 0.5
            tcy = target[:, 1] + th * 0.5
            out = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / phh,
                             jnp.log(tw / pw), jnp.log(th / phh)], axis=1)
            return out / var
        # decode_center_size: target is [M, 4] or 3-D with priors broadcast
        # along `axis` (reference: [N, M, 4] for axis=1, [M, N, 4] for axis=0)
        if target.ndim == 3:
            if axis == 0:
                pw_, phh_, pcx_, pcy_ = (v[:, None] for v in (pw, phh, pcx, pcy))
            else:
                pw_, phh_, pcx_, pcy_ = (v[None, :] for v in (pw, phh, pcx, pcy))
        else:
            pw_, phh_, pcx_, pcy_ = pw, phh, pcx, pcy
        d = target * var
        dcx = d[..., 0] * pw_ + pcx_
        dcy = d[..., 1] * phh_ + pcy_
        dw = jnp.exp(d[..., 2]) * pw_
        dh = jnp.exp(d[..., 3]) * phh_
        return jnp.stack([dcx - dw * 0.5, dcy - dh * 0.5,
                          dcx + dw * 0.5 - norm, dcy + dh * 0.5 - norm], axis=-1)

    return apply_op("box_coder", fn, prior_box, target_box)


from .ops_detection import *  # noqa: F401,F403,E402 — detection op family
