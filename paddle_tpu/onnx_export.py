"""ONNX protobuf export: jaxpr -> ONNX ModelProto bytes, no onnx package.

Parity: python/paddle/onnx/export.py (which shells out to paddle2onnx's
Program->ONNX translator). TPU design: the framework's graph IR is a
traced jaxpr, whose primitive set is small and closed — each equation
maps to one-or-few ONNX nodes, and the protobuf wire format (varint +
length-delimited fields) is simple enough to emit directly. Covered
primitives: dot_general (matmul), elementwise arithmetic/activations,
reductions, reshape/transpose/broadcast, conv_general_dilated, cast,
max-pool reduce_window; call-like primitives (pjit/custom_jvp/remat) are
inlined recursively. Tests parse the output with protoc-generated
bindings to validate the encoding (tests/test_onnx_export.py).
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.extend import core as jex_core

__all__ = ["export_onnx", "OnnxExportError"]


class OnnxExportError(NotImplementedError):
    pass


# ---------------------------------------------------------------------------
# minimal protobuf wire-format writer
# ---------------------------------------------------------------------------


def _varint(n: int) -> bytes:
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _f_int(field: int, v: int) -> bytes:
    return _tag(field, 0) + _varint(int(v))


def _f_bytes(field: int, v: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(v)) + v


def _f_str(field: int, v: str) -> bytes:
    return _f_bytes(field, v.encode())


def _f_float(field: int, v: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", float(v))


def _f_packed_ints(field: int, vs: Sequence[int]) -> bytes:
    payload = b"".join(_varint(int(v)) for v in vs)
    return _f_bytes(field, payload)


# ONNX TensorProto.DataType
_DT = {"float32": 1, "uint8": 2, "int8": 3, "int16": 5, "int32": 6,
       "int64": 7, "bool": 9, "float16": 10, "float64": 11, "bfloat16": 16}


def _tensor_proto(name: str, arr: np.ndarray) -> bytes:
    dt = _DT[str(arr.dtype)]
    msg = b"".join(_f_int(1, d) for d in arr.shape)
    msg += _f_int(2, dt)
    msg += _f_str(8, name)
    msg += _f_bytes(9, np.ascontiguousarray(arr).tobytes())  # raw_data
    return msg


def _value_info(name: str, shape: Sequence, dtype: str) -> bytes:
    dims = b""
    for i, d in enumerate(shape):
        if d is None or (isinstance(d, int) and d < 0):
            dims += _f_bytes(1, _f_str(2, f"dyn_{i}"))  # Dimension.dim_param
        else:
            dims += _f_bytes(1, _f_int(1, int(d)))      # Dimension.dim_value
    shape_msg = dims
    tensor_type = _f_int(1, _DT[dtype]) + _f_bytes(2, shape_msg)
    type_proto = _f_bytes(1, tensor_type)
    return _f_str(1, name) + _f_bytes(2, type_proto)


_ATTR_INT, _ATTR_STR, _ATTR_INTS = 2, 3, 7  # AttributeProto.AttributeType


def _attr_int(name: str, v: int) -> bytes:
    return _f_str(1, name) + _f_int(3, v) + _f_int(20, _ATTR_INT)


def _attr_ints(name: str, vs: Sequence[int]) -> bytes:
    return _f_str(1, name) + b"".join(_f_int(8, v) for v in vs) + _f_int(20, _ATTR_INTS)


def _attr_str(name: str, v: str) -> bytes:
    return _f_str(1, name) + _f_bytes(4, v.encode()) + _f_int(20, _ATTR_STR)


def _node(op_type: str, inputs: Sequence[str], outputs: Sequence[str],
          attrs: Sequence[bytes] = (), name: str = "") -> bytes:
    msg = b"".join(_f_str(1, i) for i in inputs)
    msg += b"".join(_f_str(2, o) for o in outputs)
    if name:
        msg += _f_str(3, name)
    msg += _f_str(4, op_type)
    msg += b"".join(_f_bytes(5, a) for a in attrs)
    return msg


# ---------------------------------------------------------------------------
# jaxpr -> ONNX graph
# ---------------------------------------------------------------------------


class _Graph:
    def __init__(self):
        self.nodes: List[bytes] = []
        self.initializers: List[bytes] = []
        self.counter = 0

    def fresh(self, hint="t"):
        self.counter += 1
        return f"{hint}_{self.counter}"

    def add(self, op, inputs, outputs, attrs=(), name=""):
        self.nodes.append(_node(op, inputs, outputs, attrs, name or self.fresh(op)))

    def const(self, arr: np.ndarray, hint="const"):
        nm = self.fresh(hint)
        self.initializers.append(_tensor_proto(nm, arr))
        return nm


_ELEMENTWISE = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
    "max": "Max", "min": "Min", "pow": "Pow",
    "exp": "Exp", "log": "Log", "tanh": "Tanh", "neg": "Neg",
    "abs": "Abs", "sqrt": "Sqrt", "sign": "Sign", "floor": "Floor",
    "ceil": "Ceil", "logistic": "Sigmoid", "erf": "Erf", "sin": "Sin",
    "cos": "Cos", "is_finite": "IsInf",  # handled specially below if needed
}

_REDUCE = {"reduce_sum": "ReduceSum", "reduce_max": "ReduceMax",
           "reduce_min": "ReduceMin", "reduce_prod": "ReduceProd"}


def _np(v):
    return np.asarray(v)


def _convert_jaxpr(jaxpr, g: _Graph, env: Dict[Any, str]):
    """Emit nodes for each equation; env maps jax vars -> ONNX value names."""

    def read(atom):
        if isinstance(atom, jex_core.Literal):
            return g.const(_np(atom.val), "lit")
        return env[atom]

    def write(var, name):
        env[var] = name

    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        ins = [read(a) for a in eqn.invars]
        outs = [g.fresh(prim) for _ in eqn.outvars]

        if prim in ("jit", "pjit", "closed_call", "custom_jvp_call", "custom_vjp_call",
                    "custom_jvp_call_jaxpr", "remat", "checkpoint", "custom_vjp_call_jaxpr"):
            sub = (eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
                   or eqn.params.get("fun_jaxpr"))
            sub_jaxpr = sub.jaxpr if hasattr(sub, "jaxpr") else sub
            sub_env: Dict[Any, str] = {}
            consts = getattr(sub, "consts", [])
            for cv, cval in zip(sub_jaxpr.constvars, consts):
                sub_env[cv] = g.const(_np(cval), "const")
            for iv, nm in zip(sub_jaxpr.invars, ins):
                sub_env[iv] = nm
            _convert_jaxpr(sub_jaxpr, g, sub_env)
            for ov, outer in zip(sub_jaxpr.outvars, eqn.outvars):
                env[outer] = sub_env[ov] if not isinstance(ov, jex_core.Literal) \
                    else g.const(_np(ov.val), "lit")
            continue

        if prim in _ELEMENTWISE and prim != "is_finite":
            g.add(_ELEMENTWISE[prim], ins, outs)
        elif prim in ("gt", "lt", "ge", "le", "eq"):
            g.add({"gt": "Greater", "lt": "Less", "ge": "GreaterOrEqual",
                   "le": "LessOrEqual", "eq": "Equal"}[prim], ins, outs)
        elif prim == "ne":
            e = g.fresh("eq")
            g.add("Equal", ins, [e])
            g.add("Not", [e], outs)
        elif prim in ("and", "or", "xor", "not"):
            g.add({"and": "And", "or": "Or", "xor": "Xor", "not": "Not"}[prim],
                  ins, outs)
        elif prim == "integer_pow":
            y = g.const(_np(np.float32(eqn.params["y"])))
            g.add("Pow", [ins[0], y], outs)
        elif prim == "rsqrt":
            s = g.fresh("sqrt")
            g.add("Sqrt", ins, [s])
            one = g.const(_np(np.float32(1.0)))
            g.add("Div", [one, s], outs)
        elif prim == "dot_general":
            ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
            lhs_ndim = len(eqn.invars[0].aval.shape)
            rhs_ndim = len(eqn.invars[1].aval.shape)
            # standard matmul patterns only: contract last of lhs with
            # first non-batch of rhs, batch dims leading and aligned
            if (list(lb) == list(range(len(lb))) and list(rb) == list(lb)
                    and list(lc) == [lhs_ndim - 1]
                    and list(rc) == [len(rb)] ):
                g.add("MatMul", ins, outs)
            elif (not lb and not rb and list(lc) == [lhs_ndim - 1]
                  and list(rc) == [0]):
                g.add("MatMul", ins, outs)
            elif not lb and not rb and list(lc) == [lhs_ndim - 1] and list(rc) == [rhs_ndim - 1]:
                # x @ y.T — insert a Transpose on rhs
                tr = g.fresh("trans")
                g.add("Transpose", [ins[1]], [tr],
                      [_attr_ints("perm", list(range(rhs_ndim - 2)) + [rhs_ndim - 1, rhs_ndim - 2])])
                g.add("MatMul", [ins[0], tr], outs)
            else:
                raise OnnxExportError(f"unsupported dot_general layout {eqn.params['dimension_numbers']}")
        elif prim in _REDUCE:
            axes = [int(a) for a in eqn.params["axes"]]
            g.add(_REDUCE[prim], ins, outs,
                  [_attr_ints("axes", axes), _attr_int("keepdims", 0)])
        elif prim == "reshape":
            shape = g.const(_np(np.asarray(eqn.params["new_sizes"], np.int64)))
            g.add("Reshape", [ins[0], shape], outs)
        elif prim == "squeeze":
            shape = g.const(_np(np.asarray(eqn.outvars[0].aval.shape, np.int64)))
            g.add("Reshape", [ins[0], shape], outs)
        elif prim == "expand_dims":
            shape = g.const(_np(np.asarray(eqn.outvars[0].aval.shape, np.int64)))
            g.add("Reshape", [ins[0], shape], outs)
        elif prim == "transpose":
            g.add("Transpose", ins, outs,
                  [_attr_ints("perm", [int(p) for p in eqn.params["permutation"]])])
        elif prim == "broadcast_in_dim":
            in_shape = eqn.invars[0].aval.shape
            out_shape = eqn.params["shape"]
            bdims = eqn.params["broadcast_dimensions"]
            # reshape to singleton-padded shape, then Expand broadcasts
            padded = [1] * len(out_shape)
            for src_dim, dst_dim in enumerate(bdims):
                padded[dst_dim] = in_shape[src_dim]
            rs = g.fresh("rs")
            shape1 = g.const(_np(np.asarray(padded, np.int64)))
            g.add("Reshape", [ins[0], shape1], [rs])
            shape2 = g.const(_np(np.asarray(out_shape, np.int64)))
            g.add("Expand", [rs, shape2], outs)
        elif prim == "convert_element_type":
            g.add("Cast", ins, outs,
                  [_attr_int("to", _DT[str(np.dtype(eqn.params["new_dtype"]))])])
        elif prim == "stop_gradient" or prim == "copy":
            g.add("Identity", ins, outs)
        elif prim == "select_n" and len(ins) == 3:
            # select_n(pred, on_false, on_true) -> Where(pred, on_true, on_false)
            g.add("Where", [ins[0], ins[2], ins[1]], outs)
        elif prim == "conv_general_dilated":
            dn = eqn.params["dimension_numbers"]
            if dn.lhs_spec != tuple(range(len(dn.lhs_spec))):
                raise OnnxExportError("conv export supports NCHW/OIHW layouts only")
            strides = [int(s) for s in eqn.params["window_strides"]]
            pads = eqn.params["padding"]
            pad_attr = [int(p[0]) for p in pads] + [int(p[1]) for p in pads]
            dil = [int(d) for d in eqn.params["rhs_dilation"]]
            groups = int(eqn.params["feature_group_count"])
            g.add("Conv", ins, outs,
                  [_attr_ints("strides", strides), _attr_ints("pads", pad_attr),
                   _attr_ints("dilations", dil), _attr_int("group", groups)])
        elif prim == "reduce_window_max":
            wd = eqn.params["window_dimensions"]
            ws = eqn.params["window_strides"]
            pads = eqn.params.get("padding", ((0, 0),) * len(wd))
            if wd[0] != 1 or wd[1] != 1:
                raise OnnxExportError("reduce_window_max: only NCHW pooling supported")
            g.add("MaxPool", ins, outs,
                  [_attr_ints("kernel_shape", [int(d) for d in wd[2:]]),
                   _attr_ints("strides", [int(s) for s in ws[2:]]),
                   _attr_ints("pads", [int(p[0]) for p in pads[2:]] + [int(p[1]) for p in pads[2:]])])
        else:
            raise OnnxExportError(
                f"jax primitive {prim!r} has no ONNX mapping yet (op subset: "
                "matmul/elementwise/reduce/reshape/transpose/broadcast/conv/pool)")

        for var, nm in zip(eqn.outvars, outs):
            write(var, nm)


def export_onnx(fn, example_inputs: Sequence, params: Optional[Dict[str, Any]] = None,
                model_name: str = "paddle_tpu", opset: int = 12,
                input_shapes: Optional[Sequence[Sequence]] = None) -> bytes:
    """Trace ``fn(*example_inputs)`` and return ONNX ModelProto bytes.

    params: optional name->array dict exported as initializers; when given,
    ``fn`` must accept (params, *inputs). opset defaults to 12 — the last
    opset where ReduceSum keeps its ``axes`` attribute (axes moved to an
    input in 13). input_shapes: optional per-input shapes overriding the
    traced ones for the graph input declarations; None/-1 entries become
    symbolic dim_params (dynamic batch etc.).
    """
    params = params or {}
    if params:
        closed = jax.make_jaxpr(fn)(params, *example_inputs)
    else:
        closed = jax.make_jaxpr(fn)(*example_inputs)
    jaxpr = closed.jaxpr

    g = _Graph()
    env: Dict[Any, str] = {}
    for cv, cval in zip(jaxpr.constvars, closed.consts):
        env[cv] = g.const(_np(cval), "const")

    flat_params, ptree = jax.tree.flatten(params)
    pnames = [f"param_{i}" for i in range(len(flat_params))]
    n_param_vars = len(flat_params)
    invars = list(jaxpr.invars)
    for i, (v, arr) in enumerate(zip(invars[:n_param_vars], flat_params)):
        nm = pnames[i]
        g.initializers.append(_tensor_proto(nm, np.asarray(arr)))
        env[v] = nm
    input_infos = []
    for i, v in enumerate(invars[n_param_vars:]):
        nm = f"input_{i}"
        env[v] = nm
        shp = (input_shapes[i] if input_shapes is not None and i < len(input_shapes)
               else v.aval.shape)
        input_infos.append(_value_info(nm, shp, str(v.aval.dtype)))

    _convert_jaxpr(jaxpr, g, env)

    output_infos = []
    out_names = []
    for i, v in enumerate(jaxpr.outvars):
        nm = env[v] if not isinstance(v, jex_core.Literal) else g.const(_np(v.val))
        out_names.append(nm)
        output_infos.append(_value_info(nm, v.aval.shape, str(v.aval.dtype)))

    graph = b"".join(_f_bytes(1, n) for n in g.nodes)
    graph += _f_str(2, model_name)
    graph += b"".join(_f_bytes(5, t) for t in g.initializers)
    graph += b"".join(_f_bytes(11, i) for i in input_infos)
    graph += b"".join(_f_bytes(12, o) for o in output_infos)

    opset_import = _f_str(1, "") + _f_int(2, opset)
    model = _f_int(1, 8)                      # ir_version
    model += _f_str(2, "paddle_tpu")          # producer_name
    model += _f_str(3, "0.1.0")               # producer_version
    model += _f_bytes(7, graph)
    model += _f_bytes(8, opset_import)
    return model
