from . import autograd, dtype, flags
from .tensor import Parameter, Tensor

__all__ = ["Tensor", "Parameter", "autograd", "dtype", "flags"]
