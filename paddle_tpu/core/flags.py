"""Process-level flag registry.

TPU-native equivalent of the reference's exported-flags system
(reference: paddle/common/flags.cc — ~180 ``PHI_DEFINE_EXPORTED_*`` flags,
paddle/common/flags.h:38). Flags are settable programmatically via
``set_flags`` or by environment variables ``FLAGS_<name>`` read at first
access, mirroring the reference's env-var override semantics.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional

_lock = threading.Lock()


class _Flag:
    __slots__ = ("name", "default", "value", "help", "parser", "env_read")

    def __init__(self, name: str, default: Any, help: str, parser: Callable[[str], Any]):
        self.name = name
        self.default = default
        self.value = default
        self.help = help
        self.parser = parser
        self.env_read = False


_REGISTRY: Dict[str, _Flag] = {}


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


def define_flag(name: str, default: Any, help: str = "") -> None:
    """Register a flag. Type is inferred from the default value."""
    if isinstance(default, bool):
        parser: Callable[[str], Any] = _parse_bool
    elif isinstance(default, int):
        parser = int
    elif isinstance(default, float):
        parser = float
    else:
        parser = str
    with _lock:
        if name not in _REGISTRY:
            _REGISTRY[name] = _Flag(name, default, help, parser)


def get_flags(names) -> Dict[str, Any]:
    if isinstance(names, str):
        names = [names]
    out = {}
    for name in names:
        out[name] = _get(name)
    return out


def _strip(name: str) -> str:
    # accept the reference's spelled form: paddle.set_flags({'FLAGS_x': v})
    return name[6:] if name.startswith("FLAGS_") else name


def _get(name: str) -> Any:
    name = _strip(name)
    flag = _REGISTRY.get(name)
    if flag is None:
        raise KeyError(f"unknown flag: {name!r}")
    with _lock:
        if not flag.env_read:
            env = os.environ.get(f"FLAGS_{name}")
            if env is not None:
                flag.value = flag.parser(env)
            flag.env_read = True
        return flag.value


def set_flags(flags: Dict[str, Any]) -> None:
    for name, value in flags.items():
        name = _strip(name)
        flag = _REGISTRY.get(name)
        if flag is None:
            raise KeyError(f"unknown flag: {name!r}")
        with _lock:
            flag.env_read = True
            flag.value = value


def flag(name: str) -> Any:
    """Fast accessor used on hot paths."""
    return _get(name)


# ---------------------------------------------------------------------------
# Core flags (subset of the reference's surface that is meaningful on TPU).
# ---------------------------------------------------------------------------
define_flag("check_nan_inf", False, "Check every op output for NaN/Inf (reference: FLAGS_check_nan_inf).")
define_flag("check_nan_inf_level", 0, "0: error on nan/inf; 1: warn; (reference: nan_inf_utils_detail).")
define_flag("eager_op_jit", True, "Cache-jit eager single-op executables (PJRT executable cache).")
define_flag("benchmark", False, "Synchronize after every op for timing.")
define_flag("tpu_matmul_precision", "default", "XLA matmul precision: default|high|highest.")
define_flag("use_stride_kernel", False, "Unused on TPU; kept for API parity.")
define_flag("embedding_deterministic", 0, "Deterministic embedding grad (XLA scatter is deterministic).")
define_flag("distributed_timeout_s", 1800, "Collective/rendezvous timeout seconds.")
define_flag("allocator_strategy", "xla", "Kept for parity; PJRT owns device memory.")
define_flag("log_level", 0, "Framework verbose log level (VLOG equivalent).")
