"""Dtype system.

TPU-native equivalent of the reference's DataType enum
(reference: paddle/phi/common/data_type.h; python/paddle/framework/dtype.py).
Dtypes are thin aliases over numpy/jnp dtypes so they flow through XLA
unchanged; ``bfloat16`` is first-class (the TPU-native half type).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical dtype objects (jnp dtypes are numpy dtype instances).
bool = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128
float8_e4m3fn = jnp.float8_e4m3fn
float8_e5m2 = jnp.float8_e5m2

_NAME_TO_DTYPE = {
    "bool": bool,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "float32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
    "float8_e4m3fn": float8_e4m3fn,
    "float8_e5m2": float8_e5m2,
}

_FLOATING = {float16, bfloat16, float32, float64, float8_e4m3fn, float8_e5m2}
_INTEGER = {uint8, int8, int16, int32, int64}

_default_dtype = [jnp.dtype(float32)]


def convert_dtype(dtype):
    """Normalize a user-supplied dtype (str / np dtype / jnp dtype) to the
    canonical np.dtype for this backend (x64 disabled ⇒ int64→int32,
    float64→float32, mirroring XLA's default type widths)."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _NAME_TO_DTYPE:
            raise ValueError(f"unsupported dtype string: {dtype!r}")
        d = np.dtype(_NAME_TO_DTYPE[dtype])
    else:
        d = np.dtype(dtype)
    import jax

    return np.dtype(jax.dtypes.canonicalize_dtype(d))


def set_default_dtype(dtype):
    d = convert_dtype(dtype)
    if d not in (np.dtype(float32), np.dtype(float64), np.dtype(float16), np.dtype(bfloat16)):
        raise ValueError("default dtype must be a floating dtype")
    _default_dtype[0] = d


def get_default_dtype():
    return _default_dtype[0]


def is_floating_point(dtype) -> "bool":
    return np.dtype(dtype) in {np.dtype(d) for d in _FLOATING}


def is_integer(dtype) -> "bool":
    return np.dtype(dtype) in {np.dtype(d) for d in _INTEGER}
