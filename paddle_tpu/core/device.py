"""Device management.

Parity: python/paddle/device/__init__.py (set_device/get_device) +
phi/backends/device_manager.h DeviceManager. TPU design: devices are PJRT
devices enumerated by jax; ``set_device`` installs a default-device config
so subsequent array placements land there. The TPU is first-class (the
reference's CustomDevice plugin inversion — SURVEY §7.1).
"""

from __future__ import annotations

import jax

_current = [None]  # None = jax default


class Place:
    def __init__(self, device_id: int = 0):
        self._id = device_id

    def get_device_id(self):
        return self._id

    def __repr__(self):
        return f"{type(self).__name__}({self._id})"


class CPUPlace(Place):
    pass


class TPUPlace(Place):
    pass


class CUDAPlace(Place):
    """Kept for API parity; maps to the accelerator device on TPU builds."""


def _platform_devices(kind: str):
    try:
        return jax.devices(kind)
    except RuntimeError:
        return []


def set_device(device: str):
    """device: 'cpu', 'tpu', 'tpu:0', 'gpu'/'gpu:0' (alias for accelerator)."""
    name, _, idx = device.partition(":")
    idx = int(idx) if idx else 0
    name = name.lower()
    if name in ("tpu", "gpu", "xpu", "npu", "custom_cpu", "axon"):
        devs = [d for d in jax.devices() if d.platform != "cpu"] or jax.devices()
    elif name == "cpu":
        devs = _platform_devices("cpu")
    else:
        raise ValueError(f"unknown device {device!r}")
    if not devs:
        raise RuntimeError(f"no devices for {device!r}")
    dev = devs[min(idx, len(devs) - 1)]
    _current[0] = dev
    jax.config.update("jax_default_device", dev)
    return dev


def get_device() -> str:
    dev = _current[0]
    if dev is None:
        dev = jax.devices()[0]
    plat = dev.platform
    name = "cpu" if plat == "cpu" else "tpu"
    return f"{name}:{dev.id}" if name != "cpu" else "cpu"


def get_default_device():
    return _current[0] or jax.devices()[0]


def device_count() -> int:
    return len(jax.devices())


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True


def synchronize():
    """Block until all enqueued work completes (parity: device.synchronize)."""
    for d in jax.live_arrays():
        try:
            d.block_until_ready()
        except Exception:
            pass
