"""Eager Tensor: a Paddle-shaped handle over a (lazy, async) jax.Array.

TPU-native equivalent of the reference's eager Tensor
(reference: paddle/fluid/pybind/eager.cc — pytype creation,
eager_method.cc — methods, phi/core/dense_tensor.h:37 DenseTensor).

The payload is a ``jax.Array`` (PJRT buffer, asynchronously computed), so
every op is an XLA dispatch and host code never blocks until a value is
observed (``numpy()``/``item()``). Autograd metadata (``stop_gradient``,
``_grad_node``, ``grad``) lives on the handle like the reference's
AutogradMeta. Most operator methods are patched in by
``paddle_tpu.ops`` at import time (mirroring the reference's generated
method registration, python/paddle/tensor/__init__.py).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtypes
from .autograd import backward as _backward_engine

# Set by jit.sot_lite: intercepts Tensor concretization (item/bool/int/
# float) so to_static can graph-break instead of erroring on traced values.
_concretize_hook = [None]


class Tensor:
    __slots__ = (
        "_data",
        "stop_gradient",
        "_grad_data",
        "_grad_node",
        "_out_slot",
        "name",
        "persistable",
        "_hooks",
        "placements",
        "process_mesh",
        "_prov",  # auto-shard dataflow provenance (distributed/auto_shard.py)
        # conv+BN+ReLU fusion peephole tags (nn/layers_conv_norm.py):
        # a qualifying Conv2D output carries (input, layer) so the next
        # BatchNorm can route the pair to the Pallas fused kernel; a
        # frozen-stats fused output carries a relu re-dispatch closure
        "_fused_conv_src",
        "_fused_relu_rerun",
        # training-mode chain fusion: a fused conv+BN output carries
        # (raw_conv_out, mean, var, gamma, beta, eps, relu_applied) so
        # the NEXT qualifying conv can run the normalize(+relu) as its
        # kernel prologue and read the raw tensor instead
        "_fused_bn_pending",
        "__weakref__",
    )

    _next_id = [0]

    def __init__(self, data, stop_gradient: bool = True, name: Optional[str] = None):
        if isinstance(data, Tensor):
            data = data._data
        if not isinstance(data, (jax.Array, jax.core.Tracer)):
            data = jnp.asarray(data)
        self._data = data
        self.stop_gradient = stop_gradient
        self._grad_data = None
        self._grad_node = None
        self._out_slot = 0
        if name is None:
            Tensor._next_id[0] += 1
            name = f"generated_tensor_{Tensor._next_id[0]}"
        self.name = name
        self.persistable = False
        self._hooks = []
        self.placements = None  # DistTensor metadata (set by distributed.api)
        self.process_mesh = None

    # ------------------------------------------------------------------
    # Introspection
    # ------------------------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def ndim(self) -> int:
        return self._data.ndim

    @property
    def size(self) -> int:
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    @property
    def place(self):
        try:
            return str(next(iter(self._data.devices())))
        except Exception:
            return "traced"

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-D tensor")
        return self._data.shape[0]

    def __repr__(self):
        grad_flag = self.stop_gradient
        try:
            body = np.array2string(np.asarray(self._data), precision=6, separator=", ")
        except Exception:
            body = f"<traced {self._data}>"
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name if hasattr(self.dtype, 'name') else self.dtype}, "
            f"stop_gradient={grad_flag},\n       {body})"
        )

    # ------------------------------------------------------------------
    # Host access (sync points)
    # ------------------------------------------------------------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self._data)

    def _item(self):
        """Concretization choke point. Under a to_static trace the SOT-lite
        hook (jit/sot_lite.py) intercepts this: a traced value becomes a
        compiled guard and the recorded outcome steers Python control flow
        (the reference's graph-break mechanism, eval_frame_callback.py:54)."""
        hook = _concretize_hook[0]
        if hook is not None:
            handled, v = hook(self._data)
            if handled:
                return v
        return self._data.item()

    def item(self):
        return self._item()

    def tolist(self):
        return np.asarray(self._data).tolist()

    def __float__(self):
        return float(self._item())

    def __int__(self):
        return int(self._item())

    def __bool__(self):
        if self.size != 1:
            raise ValueError("The truth value of a multi-element Tensor is ambiguous")
        return builtins_bool(self._item())

    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    # ------------------------------------------------------------------
    # Autograd
    # ------------------------------------------------------------------
    @property
    def grad(self) -> Optional["Tensor"]:
        if self._grad_data is None:
            return None
        return Tensor(self._grad_data, stop_gradient=True, name=self.name + "@GRAD")

    @grad.setter
    def grad(self, value):
        if value is None:
            self._grad_data = None
        else:
            self._grad_data = value._data if isinstance(value, Tensor) else jnp.asarray(value)

    def _accumulate_grad(self, gdata):
        if gdata.dtype != self._data.dtype:
            gdata = gdata.astype(self._data.dtype)
        for hook in self._hooks:
            out = hook(Tensor(gdata, stop_gradient=True))
            if out is not None:
                gdata = out._data if isinstance(out, Tensor) else jnp.asarray(out)
        if self._grad_data is None:
            self._grad_data = gdata
        else:
            self._grad_data = self._grad_data + gdata

    def backward(self, grad_tensor=None, retain_graph: bool = False):
        _backward_engine([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self._grad_data = None

    def clear_gradient(self, set_to_zero: bool = False):
        if set_to_zero and self._grad_data is not None:
            self._grad_data = jnp.zeros_like(self._grad_data)
        else:
            self._grad_data = None

    def register_hook(self, hook):
        """Gradient hook on a leaf (parity: Tensor.register_hook / eager hooks)."""
        self._hooks.append(hook)

        class _Handle:
            def remove(_s):
                try:
                    self._hooks.remove(hook)
                except ValueError:
                    pass

        return _Handle()

    def detach(self) -> "Tensor":
        return Tensor(self._data, stop_gradient=True, name=self.name + "@detached")

    def detach_(self) -> "Tensor":
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from ..ops.dispatch import apply_op

        return apply_op("clone", lambda x: x + jnp.zeros((), x.dtype), self)

    # ------------------------------------------------------------------
    # Data movement / casting helpers (others patched in by ops)
    # ------------------------------------------------------------------
    def astype(self, dtype) -> "Tensor":
        from ..ops.dispatch import apply_op

        d = dtypes.convert_dtype(dtype)
        return apply_op("cast", lambda x: x.astype(d), self)

    def cast(self, dtype) -> "Tensor":
        return self.astype(dtype)

    def _replace_(self, new: "Tensor") -> "Tensor":
        """In-place rebind (used by inplace ops / __setitem__)."""
        self._data = new._data
        self._grad_node = new._grad_node
        self._out_slot = new._out_slot
        self.stop_gradient = new.stop_gradient
        return self

    def copy_(self, other: "Tensor") -> "Tensor":
        self._data = jnp.asarray(other._data, self._data.dtype)
        return self

    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._data
        self._data = jnp.asarray(value, self._data.dtype).reshape(self._data.shape)
        return self

    def pin_memory(self):
        return self

    def cpu(self):
        return Tensor(jax.device_put(self._data, jax.devices("cpu")[0]), self.stop_gradient)

    def to(self, *args, **kwargs):
        # Accept dtype-like or device-like single arg, Paddle-style.
        for a in list(args) + list(kwargs.values()):
            try:
                d = dtypes.convert_dtype(a)
                return self.astype(d)
            except (ValueError, TypeError):
                continue
        return self

    @property
    def T(self):
        from ..ops.dispatch import apply_op

        axes = tuple(reversed(range(self.ndim)))
        return apply_op("transpose", lambda x: jnp.transpose(x, axes), self)


def builtins_bool(x):
    return bool(x)


class Parameter(Tensor):
    """Trainable tensor (parity: python/paddle/base/framework.py Parameter /
    EagerParamBase). ``stop_gradient`` defaults to False; ``trainable``
    toggles it."""

    __slots__ = ("optimize_attr", "regularizer", "need_clip", "is_distributed", "sequence_parallel")

    def __init__(self, data, trainable: bool = True, name: Optional[str] = None):
        super().__init__(data, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed = False
        self.sequence_parallel = False
        self.placements = None
        self.process_mesh = None

    @property
    def trainable(self) -> bool:
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v: bool):
        self.stop_gradient = not v
