"""Loader for the native C++ runtime library (csrc/).

The reference framework's runtime substrate (store, allocators, tracer)
is C++ (paddle/phi/core/...); ours is too — csrc/ builds
libpaddle_tpu_native.so, bound here via ctypes (no pybind11 in the
image). The library is built lazily on first use and cached; every
consumer has a pure-Python fallback so the framework still works where
no C++ toolchain exists.
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess
import threading

_lock = threading.Lock()
_lib = None
_tried = False

_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "csrc")
_SO = os.path.join(_CSRC, "build", "libpaddle_tpu_native.so")

# callback signature for the native job scheduler: (job_id, user_tag, ctx)
JSCHED_CALLBACK = ctypes.CFUNCTYPE(None, ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p)


def _stale() -> bool:
    if not os.path.exists(_SO):
        return True
    so_mtime = os.path.getmtime(_SO)
    return any(
        f.endswith(".cc") and os.path.getmtime(os.path.join(_CSRC, f)) > so_mtime
        for f in os.listdir(_CSRC))


def _build() -> bool:
    if not os.path.isdir(_CSRC) or shutil.which("make") is None:
        return False
    try:
        subprocess.run(
            ["make", "-C", _CSRC, f"-j{os.cpu_count() or 2}"],
            check=True, capture_output=True, timeout=300)
        return os.path.exists(_SO)
    except (subprocess.SubprocessError, OSError):
        return False


def _declare(lib: ctypes.CDLL) -> None:
    c = ctypes
    lib.pts_server_start.restype = c.c_void_p
    lib.pts_server_start.argtypes = [c.c_int]
    lib.pts_server_port.restype = c.c_int
    lib.pts_server_port.argtypes = [c.c_void_p]
    lib.pts_server_stop.argtypes = [c.c_void_p]
    lib.pts_client_new.restype = c.c_void_p
    lib.pts_client_new.argtypes = [c.c_char_p, c.c_int, c.c_long]
    lib.pts_client_free.argtypes = [c.c_void_p]
    lib.pts_set.restype = c.c_int
    lib.pts_set.argtypes = [c.c_void_p, c.c_char_p, c.c_char_p, c.c_int]
    lib.pts_get.restype = c.c_int
    lib.pts_get.argtypes = [c.c_void_p, c.c_char_p, c.c_long,
                            c.POINTER(c.c_void_p), c.POINTER(c.c_int)]
    lib.pts_buf_free.argtypes = [c.c_void_p]
    lib.pts_add.restype = c.c_longlong
    lib.pts_add.argtypes = [c.c_void_p, c.c_char_p, c.c_longlong]
    lib.pts_wait.restype = c.c_int
    lib.pts_wait.argtypes = [c.c_void_p, c.c_char_p, c.c_long]
    lib.pts_check.restype = c.c_int
    lib.pts_check.argtypes = [c.c_void_p, c.c_char_p]
    lib.pts_delete_key.restype = c.c_int
    lib.pts_delete_key.argtypes = [c.c_void_p, c.c_char_p]
    lib.pts_num_keys.restype = c.c_longlong
    lib.pts_num_keys.argtypes = [c.c_void_p]
    # arena allocator (csrc/arena.cc)
    lib.pta_create.restype = c.c_void_p
    lib.pta_create.argtypes = [c.c_uint64]
    lib.pta_destroy.argtypes = [c.c_void_p]
    lib.pta_alloc.restype = c.c_void_p
    lib.pta_alloc.argtypes = [c.c_void_p, c.c_uint64]
    lib.pta_free.restype = c.c_int
    lib.pta_free.argtypes = [c.c_void_p, c.c_void_p]
    for fn in ("pta_allocated", "pta_peak", "pta_capacity", "pta_largest_free"):
        getattr(lib, fn).restype = c.c_uint64
        getattr(lib, fn).argtypes = [c.c_void_p]
    lib.pta_reset_peak.argtypes = [c.c_void_p]
    # host tracer (csrc/host_tracer.cc)
    lib.pth_tracer_init.restype = c.c_int
    lib.pth_tracer_init.argtypes = [c.c_uint64]
    lib.pth_tracer_enable.argtypes = [c.c_int]
    lib.pth_tracer_enabled.restype = c.c_int
    lib.pth_record_begin.restype = c.c_int64
    lib.pth_record_begin.argtypes = [c.c_char_p, c.c_uint32]
    lib.pth_record_end.argtypes = [c.c_int64]
    lib.pth_record_instant.argtypes = [c.c_char_p, c.c_uint32]
    lib.pth_tracer_count.restype = c.c_uint64
    lib.pth_tracer_dropped.restype = c.c_uint64
    lib.pth_tracer_drain.restype = c.c_uint64
    lib.pth_tracer_drain.argtypes = [c.c_void_p, c.c_uint64]
    # job scheduler (csrc/job_scheduler.cc)
    lib.jsched_new.restype = c.c_void_p
    lib.jsched_new.argtypes = [c.c_int]
    lib.jsched_free.argtypes = [c.c_void_p]
    lib.jsched_add_job.restype = c.c_int64
    lib.jsched_add_job.argtypes = [c.c_void_p, c.c_int64]
    lib.jsched_add_dep.restype = c.c_int
    lib.jsched_add_dep.argtypes = [c.c_void_p, c.c_int64, c.c_int64]
    lib.jsched_run.restype = c.c_int
    lib.jsched_run.argtypes = [c.c_void_p, JSCHED_CALLBACK, c.c_void_p]
    lib.jsched_n_jobs.restype = c.c_int
    lib.jsched_n_jobs.argtypes = [c.c_void_p]


def get_native():
    """Return the loaded CDLL, building it if needed; None if unavailable.

    Disable with PADDLE_TPU_DISABLE_NATIVE=1 (forces Python fallbacks)."""
    global _lib, _tried
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("PADDLE_TPU_DISABLE_NATIVE", "0") == "1":
            return None
        if _stale() and not _build() and not os.path.exists(_SO):
            return None
        try:
            lib = ctypes.CDLL(_SO)
            _declare(lib)
            _lib = lib
        except (OSError, AttributeError):
            # AttributeError: stale .so missing newer symbols and the
            # rebuild failed — use the pure-Python fallbacks instead
            _lib = None
    return _lib


def native_available() -> bool:
    return get_native() is not None
