"""Tape-based autograd engine over lazy XLA arrays.

TPU-native re-design of the reference's eager autograd
(reference: paddle/fluid/eager/grad_node_info.h:197 GradNodeBase,
paddle/fluid/eager/backward.cc:105 RunBackward,
paddle/fluid/eager/grad_tensor_holder.cc).

Design: every differentiable eager op records one ``GradNode`` holding the
XLA-traced pullback produced by ``jax.vjp``. ``backward()`` runs an
in-degree/ready-queue traversal identical in spirit to the reference's
engine, accumulating cotangents per output slot (sum semantics) and
depositing leaf gradients on ``Tensor.grad``. The pullback itself executes
as XLA computations, so the backward pass is device-resident and async —
only the graph walk is host-side.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

_state = threading.local()


def _tls():
    if not hasattr(_state, "grad_enabled"):
        _state.grad_enabled = True
    return _state


def is_grad_enabled() -> bool:
    return _tls().grad_enabled


def set_grad_enabled(mode: bool) -> None:
    _tls().grad_enabled = mode


class no_grad:
    """Context manager / decorator disabling tape recording.

    Parity: python/paddle/base/dygraph/base.py no_grad_.
    """

    def __enter__(self):
        self._prev = is_grad_enabled()
        set_grad_enabled(False)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False

    def __call__(self, fn):
        def wrapper(*args, **kwargs):
            with no_grad():
                return fn(*args, **kwargs)

        wrapper.__name__ = getattr(fn, "__name__", "wrapped")
        return wrapper


class enable_grad:
    def __enter__(self):
        self._prev = is_grad_enabled()
        set_grad_enabled(True)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False


class Edge:
    """Connection from a GradNode input slot to its producer.

    Parity: paddle/fluid/eager/grad_node_info.h:53 Edge.
    Either points at another GradNode's output slot, or at a leaf tensor
    (grad-accumulation target; reference: eager/accumulation/).
    """

    __slots__ = ("node", "slot", "leaf")

    def __init__(self, node: Optional["GradNode"] = None, slot: int = 0, leaf=None):
        self.node = node
        self.slot = slot
        self.leaf = leaf  # Tensor (leaf accumulation target) or None


class GradNode:
    """One recorded op on the tape.

    ``vjp_fn``: cotangents-of-outputs -> cotangents-of-inputs (XLA traced).
    ``edges[i]`` describes where input-cotangent ``i`` flows.
    ``out_specs``: (shape, dtype) per output slot for zero-filling.
    ``fwd_fn``/``fwd_inputs``/``diff_idx``: re-derivation info for
    create_graph=True (double backward): the pure forward over the
    differentiable inputs, the input Tensors, and their positions — the
    backward pass is re-expressed as taped ops so grad-of-grad sees the
    residual dependence (reference: generated GradNode ops being tracked).
    """

    __slots__ = ("name", "vjp_fn", "edges", "out_specs", "hooks", "released",
                 "fwd_fn", "fwd_inputs", "fwd_datas", "diff_idx", "multi",
                 "taped_vjp")

    def __init__(self, name: str, vjp_fn: Callable, edges: List[Edge], out_specs: List[Tuple[tuple, Any]]):
        self.name = name
        self.vjp_fn = vjp_fn
        self.edges = edges
        self.out_specs = out_specs
        self.hooks: List[Callable] = []
        self.released = False
        self.fwd_fn = None
        self.fwd_inputs = None
        self.fwd_datas = None
        self.diff_idx = None
        self.multi = False
        # create_graph alternative to fwd_fn re-derivation: run a
        # user-defined backward (PyLayer) WITH the tape on; its ops become
        # differentiable (reference: py_layer.py:268 tracked backward)
        self.taped_vjp = None

    def __repr__(self):
        return f"<GradNode {self.name} n_in={len(self.edges)} n_out={len(self.out_specs)}>"


def _is_float0(x) -> bool:
    return getattr(x, "dtype", None) == jax.dtypes.float0


def backward(tensors: Sequence, grad_tensors: Optional[Sequence] = None, retain_graph: bool = False) -> None:
    """Run the tape backward from ``tensors``.

    Parity: paddle/fluid/eager/backward.cc:105 RunBackward — in-degree map
    over the grad-node graph, ready-queue traversal, per-node cotangent
    accumulation with sum semantics.
    """
    from .tensor import Tensor  # local import to avoid cycle

    roots = [t for t in tensors if isinstance(t, Tensor)]
    if grad_tensors is None:
        grad_tensors = [None] * len(roots)

    pending: dict = {}  # id(node) -> list of cotangent-or-None per output slot
    nodes: dict = {}  # id(node) -> node
    indeg: dict = {}  # id(node) -> remaining consumer count

    def seed(node: GradNode, slot: int, g):
        buf = pending.setdefault(id(node), [None] * len(node.out_specs))
        buf[slot] = g if buf[slot] is None else buf[slot] + g

    root_nodes: List[GradNode] = []
    for t, g in zip(roots, grad_tensors):
        node = t._grad_node
        if node is None:
            if not t.stop_gradient:
                gg = g._data if isinstance(g, Tensor) else (g if g is not None else jnp.ones(t._data.shape, t._data.dtype))
                t._accumulate_grad(gg)
            continue
        if g is None:
            if t._data.size != 1:
                raise RuntimeError(
                    f"grad can be implicitly created only for scalar outputs, got shape {tuple(t._data.shape)}"
                )
            gdata = jnp.ones(t._data.shape, t._data.dtype)
        else:
            gdata = g._data if isinstance(g, Tensor) else jnp.asarray(g, t._data.dtype)
        seed(node, t._out_slot, gdata)
        root_nodes.append(node)

    # Build in-degree over the subgraph reachable from the roots.
    stack = list(root_nodes)
    while stack:
        node = stack.pop()
        if id(node) in nodes:
            continue
        nodes[id(node)] = node
        indeg.setdefault(id(node), 0)
        for e in node.edges:
            if e.node is not None:
                indeg[id(e.node)] = indeg.get(id(e.node), 0) + 1
                stack.append(e.node)

    ready = deque(n for n in set(map(id, root_nodes)) if indeg[n] == 0)
    ready = deque(nodes[nid] for nid in ready)
    processed = set()

    while ready:
        node = ready.popleft()
        if id(node) in processed:
            continue
        processed.add(id(node))
        cots = pending.pop(id(node), [None] * len(node.out_specs))
        full = [
            c if c is not None else jnp.zeros(shape, dtype)
            for c, (shape, dtype) in zip(cots, node.out_specs)
        ]
        if node.released:
            raise RuntimeError(
                f"grad node {node.name} was already released; call backward(retain_graph=True) "
                "to backprop through the same graph twice"
            )
        out = full[0] if len(full) == 1 else tuple(full)
        in_cots = node.vjp_fn(out)
        for hook in node.hooks:
            in_cots = hook(in_cots)
        if not retain_graph:
            # drop BOTH the stored pullback and the re-derivation snapshots,
            # or the graph's activations stay pinned after backward
            node.vjp_fn = None
            node.fwd_fn = None
            node.fwd_inputs = None
            node.fwd_datas = None
            node.taped_vjp = None  # PyLayer ctx pins saved tensors too
            node.released = True
        for e, g in zip(node.edges, in_cots):
            if e.leaf is not None:
                if g is not None and not _is_float0(g):
                    e.leaf._accumulate_grad(g)
            elif e.node is not None:
                if g is not None and not _is_float0(g):
                    seed(e.node, e.slot, g)
                indeg[id(e.node)] -= 1
                if indeg[id(e.node)] == 0:
                    ready.append(e.node)


def _backward_create_graph(roots, grad_tensors, capture: dict):
    """Taped backward: cotangents flow as Tensors and each node's vjp is
    re-derived with ``apply_op`` over (inputs, cotangents), so the computed
    gradients carry their own grad nodes (double backward; parity:
    RunBackward with create_graph — backward ops are themselves tracked).

    NOTE: shares the traversal shape with backward() above but the per-node
    kernel differs fundamentally (Tensor cotangents + taped re-derivation
    vs raw arrays + stored pullback); changes to seeding/ordering semantics
    must be mirrored in both."""
    from .tensor import Tensor
    from ..ops.dispatch import apply_op

    pending: dict = {}
    nodes: dict = {}
    indeg: dict = {}

    def seed(node: GradNode, slot: int, g: "Tensor"):
        buf = pending.setdefault(id(node), [None] * len(node.out_specs))
        buf[slot] = g if buf[slot] is None else buf[slot] + g

    root_nodes: List[GradNode] = []
    for t, g in zip(roots, grad_tensors):
        node = t._grad_node
        if node is None:
            continue
        if g is None:
            if t._data.size != 1:
                raise RuntimeError("grad can be implicitly created only for scalar outputs")
            gt = Tensor(jnp.ones(t._data.shape, t._data.dtype), stop_gradient=True)
        else:
            gt = g if isinstance(g, Tensor) else Tensor(jnp.asarray(g, t._data.dtype), stop_gradient=True)
        seed(node, t._out_slot, gt)
        root_nodes.append(node)

    stack = list(root_nodes)
    while stack:
        node = stack.pop()
        if id(node) in nodes:
            continue
        nodes[id(node)] = node
        indeg.setdefault(id(node), 0)
        for e in node.edges:
            if e.node is not None:
                indeg[id(e.node)] = indeg.get(id(e.node), 0) + 1
                stack.append(e.node)

    ready = deque(nodes[nid] for nid in set(map(id, root_nodes)) if indeg[nid] == 0)
    processed = set()
    while ready:
        node = ready.popleft()
        if id(node) in processed:
            continue
        processed.add(id(node))
        cots = pending.pop(id(node), [None] * len(node.out_specs))
        cot_ts = [c if c is not None else Tensor(jnp.zeros(shape, dtype), stop_gradient=True)
                  for c, (shape, dtype) in zip(cots, node.out_specs)]
        if node.taped_vjp is not None:
            # user-defined backward (PyLayer) executed with the tape ON:
            # second-order grads differentiate the CUSTOM backward, not
            # vjp(forward) — STE-style PyLayers keep their semantics
            grads = node.taped_vjp(cot_ts)
            grads = list(grads) if isinstance(grads, (tuple, list)) else [grads]
            full = [g if (g is None or isinstance(g, Tensor)) else
                    Tensor(jnp.asarray(g), stop_gradient=True) for g in grads]
            full += [None] * (len(node.edges) - len(full))
            _scatter(node, full, seed, capture, indeg, ready)
            continue
        if node.fwd_fn is None:
            raise NotImplementedError(
                f"create_graph=True through node {node.name} is unsupported "
                "(no re-derivation info — e.g. custom-op nodes)")
        n_in = len(node.fwd_inputs)
        fwd_fn, multi, out_specs = node.fwd_fn, node.multi, node.out_specs

        def revjp(*args, _fwd=fwd_fn, _n=n_in, _multi=multi, _specs=out_specs):
            xs, cs = args[:_n], args[_n:]
            _, vjp = jax.vjp(_fwd, *xs)
            cs = list(cs)
            fixed = []
            ci = 0
            for shape, dtype in _specs:
                import numpy as _np

                if _np.issubdtype(_np.dtype(dtype), _np.floating) or _np.issubdtype(
                        _np.dtype(dtype), _np.complexfloating):
                    fixed.append(cs[ci])
                else:
                    fixed.append(_np.zeros(shape, jax.dtypes.float0))
                ci += 1
            out = fixed[0] if not _multi else tuple(fixed)
            res = vjp(out)
            # singleton tuples break the engine's single-output convention
            return res[0] if len(res) == 1 else res

        # run over the record-time snapshots: later in-place mutation of the
        # inputs must not change the re-derived vjp (swap data in, restore)
        saved_data = [t._data for t in node.fwd_inputs]
        for t, d in zip(node.fwd_inputs, node.fwd_datas):
            t._data = d
        try:
            diff_cots = apply_op(f"grad_{node.name}", revjp, *node.fwd_inputs, *cot_ts)
        finally:
            for t, d in zip(node.fwd_inputs, saved_data):
                t._data = d
        diff_cots = diff_cots if isinstance(diff_cots, (tuple, list)) else [diff_cots]
        # scatter diff-input cotangents back to the full edge list
        full = [None] * len(node.edges)
        for i, g in zip(node.diff_idx, diff_cots):
            full[i] = g
        _scatter(node, full, seed, capture, indeg, ready)


def _scatter(node, full, seed, capture, indeg, ready):
    """Route per-edge grad Tensors: leaves accumulate into capture (hooks
    fire), interior edges seed downstream nodes and update in-degrees."""
    for e, g in zip(node.edges, full):
        if e.leaf is not None:
            if g is not None:
                # leaf hooks (e.g. DP allreduce) must still fire; they
                # receive the live (graph-carrying) grad Tensor here
                for hook in e.leaf._hooks:
                    out = hook(g)
                    if out is not None:
                        g = out
                key = id(e.leaf)
                capture[key] = g if capture.get(key) is None else capture[key] + g
        elif e.node is not None:
            if g is not None:
                seed(e.node, e.slot, g)
            indeg[id(e.node)] -= 1
            if indeg[id(e.node)] == 0:
                ready.append(e.node)


def grad(
    outputs: Sequence,
    inputs: Sequence,
    grad_outputs: Optional[Sequence] = None,
    retain_graph: Optional[bool] = None,
    create_graph: bool = False,
    allow_unused: bool = False,
):
    """``paddle.grad`` equivalent: partial-graph gradient computation.

    Parity: paddle/fluid/eager/backward.cc:103 GeneralGrad; with
    ``create_graph=True`` the backward pass is re-derived through the tape
    so returned grads are differentiable (double backward).
    """
    from .tensor import Tensor

    outputs = list(outputs) if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = list(inputs) if isinstance(inputs, (list, tuple)) else [inputs]
    if retain_graph is None:
        retain_graph = create_graph

    if create_graph:
        roots = [t for t in outputs if isinstance(t, Tensor)]
        gts = list(grad_outputs) if grad_outputs is not None else [None] * len(roots)
        capture: dict = {}
        _backward_create_graph(roots, gts, capture)
        results = []
        for inp in inputs:
            g = capture.get(id(inp))
            if g is None:
                if allow_unused:
                    results.append(None)
                else:
                    results.append(Tensor(jnp.zeros(inp._data.shape, inp._data.dtype),
                                          stop_gradient=True))
            else:
                results.append(g)
        return results

    # Save/clear existing leaf grads of inputs, run backward, collect, restore.
    saved = [inp._grad_data for inp in inputs]
    for inp in inputs:
        inp._grad_data = None
    backward(outputs, grad_outputs, retain_graph=retain_graph)
    results = []
    for inp, old in zip(inputs, saved):
        gdata = inp._grad_data
        if gdata is None:
            if allow_unused:
                results.append(None)
            else:
                results.append(Tensor(jnp.zeros(inp._data.shape, inp._data.dtype), stop_gradient=True))
        else:
            results.append(Tensor(gdata, stop_gradient=True))
        inp._grad_data = old
    return results
