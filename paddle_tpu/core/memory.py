"""Host staging memory + device memory stats.

Reference parity: paddle/phi/core/memory/ (malloc.h, stats.h — allocated /
max-allocated counters, memory_allocated / max_memory_allocated python
surface in paddle.device.cuda). TPU design: PJRT owns HBM, so the native
allocator (csrc/arena.cc, BFC-style best-fit + coalescing) serves *host*
staging — checkpoint IO, batch collation, H2D transfer buffers — while
device stats are read from PJRT's memory_stats().
"""

from __future__ import annotations

import ctypes
import threading
from typing import Optional

import numpy as np

from .native import get_native

_DEFAULT_CAPACITY = 256 << 20  # 256 MiB staging slab


class HostArena:
    """Best-fit host arena with stats; numpy views over its allocations.

    Falls back to plain numpy allocation (with the same stats accounting)
    when the native library is unavailable.
    """

    def __init__(self, capacity: int = _DEFAULT_CAPACITY):
        self._lib = get_native()
        self._lock = threading.Lock()
        self._fallback_allocated = 0
        self._fallback_peak = 0
        self.capacity = capacity
        if self._lib is not None:
            self._h = self._lib.pta_create(capacity)
            if not self._h:
                raise MemoryError(f"HostArena: cannot reserve {capacity} bytes")
        else:
            self._h = None
        self._live = {}  # ptr-or-id -> (array ref kept alive only by caller)

    @property
    def is_native(self) -> bool:
        return self._h is not None

    def alloc_array(self, shape, dtype) -> np.ndarray:
        """Allocate a numpy array backed by the arena (native) or the heap
        (fallback). Free with `free_array` when staging is done."""
        dtype = np.dtype(dtype)
        nbytes = int(np.prod(shape)) * dtype.itemsize
        if self._h is not None:
            ptr = self._lib.pta_alloc(self._h, max(nbytes, 1))
            if not ptr:
                raise MemoryError(
                    f"HostArena: {nbytes} bytes exceeds largest free block "
                    f"({self.largest_free()} of {self.capacity})")
            buf = (ctypes.c_char * max(nbytes, 1)).from_address(ptr)
            arr = np.frombuffer(buf, dtype=dtype, count=int(np.prod(shape))).reshape(shape)
            arr.flags.writeable = True
            with self._lock:
                self._live[arr.__array_interface__["data"][0]] = ptr
            return arr
        arr = np.empty(shape, dtype)
        with self._lock:
            self._fallback_allocated += nbytes
            self._fallback_peak = max(self._fallback_peak, self._fallback_allocated)
            self._live[arr.__array_interface__["data"][0]] = nbytes
        return arr

    def free_array(self, arr: np.ndarray) -> None:
        key = arr.__array_interface__["data"][0]
        with self._lock:
            handle = self._live.pop(key, None)
        if handle is None:
            return
        if self._h is not None:
            self._lib.pta_free(self._h, handle)
        else:
            with self._lock:
                self._fallback_allocated -= handle

    def allocated(self) -> int:
        if self._h is not None:
            return int(self._lib.pta_allocated(self._h))
        return self._fallback_allocated

    def peak(self) -> int:
        if self._h is not None:
            return int(self._lib.pta_peak(self._h))
        return self._fallback_peak

    def largest_free(self) -> int:
        if self._h is not None:
            return int(self._lib.pta_largest_free(self._h))
        return self.capacity - self._fallback_allocated

    def reset_peak(self) -> None:
        if self._h is not None:
            self._lib.pta_reset_peak(self._h)
        else:
            self._fallback_peak = self._fallback_allocated

    def close(self, force: bool = False) -> None:
        with self._lock:
            live = len(self._live)
        if live and not force:
            import warnings

            warnings.warn(
                f"HostArena.close(): {live} allocation(s) still alive — "
                "slab kept to avoid use-after-free; free them or pass force=True")
            return
        if self._h is not None:
            self._lib.pta_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            import sys

            # Only force-free at interpreter teardown; a GC'd arena with live
            # alloc_array views must keep its slab (use-after-free otherwise).
            self.close(force=sys.is_finalizing())
        except Exception:
            pass


_global_arena: Optional[HostArena] = None
_arena_lock = threading.Lock()


def get_host_arena() -> HostArena:
    global _global_arena
    if _global_arena is None:
        with _arena_lock:
            if _global_arena is None:
                _global_arena = HostArena()
    return _global_arena


# ---------------------------------------------------------------------------
# Device memory stats (paddle.device.cuda.memory_allocated parity, via PJRT)
# ---------------------------------------------------------------------------


def device_memory_stats(device=None) -> dict:
    import jax

    dev = device if device is not None else jax.devices()[0]
    try:
        return dict(dev.memory_stats() or {})
    except (AttributeError, RuntimeError, jax.errors.JaxRuntimeError):
        return {}


def memory_allocated(device=None) -> int:
    """Bytes currently allocated on the device (reference:
    paddle.device.cuda.memory_allocated)."""
    return int(device_memory_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    return int(device_memory_stats(device).get("peak_bytes_in_use", 0))


def max_memory_reserved(device=None) -> int:
    stats = device_memory_stats(device)
    return int(stats.get("peak_bytes_in_use", stats.get("bytes_limit", 0)))


def memory_reserved(device=None) -> int:
    return int(device_memory_stats(device).get("bytes_limit", 0))


def memory_headroom(device=None) -> Optional[int]:
    """``bytes_limit - bytes_in_use`` — the HBM still available to the
    process — or ``None`` when the transport reports either side missing
    (CPU PJRT commonly reports nothing; the observability ledger spells
    that ``"unsupported"``). Contract: never invents a 0."""
    stats = device_memory_stats(device)
    limit = stats.get("bytes_limit")
    live = stats.get("bytes_in_use")
    if limit is None or live is None:
        return None
    return int(limit) - int(live)


def host_memory_stat_current_value(stat: str = "Allocated") -> int:
    """Reference: memory/stats.h HostMemoryStatCurrentValue."""
    arena = get_host_arena()
    if stat == "Allocated":
        return arena.allocated()
    if stat == "Reserved":
        return arena.capacity
    raise ValueError(f"unknown host memory stat {stat!r}")


def host_memory_stat_peak_value(stat: str = "Allocated") -> int:
    arena = get_host_arena()
    if stat == "Allocated":
        return arena.peak()
    if stat == "Reserved":
        return arena.capacity
    raise ValueError(f"unknown host memory stat {stat!r}")
