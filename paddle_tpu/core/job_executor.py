"""Native dependency-graph job executor.

Parity: the async-workqueue instruction execution of PirInterpreter
(paddle/fluid/framework/new_executor/pir_interpreter.cc:1508
MultiThreadRunImpl + new_executor/workqueue/) and the fleet_executor
Carrier (paddle/fluid/distributed/fleet_executor/fleet_executor.h:36).

The C++ pool (csrc/job_scheduler.cc) orders jobs by their dependency DAG;
Python callbacks that dispatch compiled XLA executables release the GIL
inside jax, so host scheduling overlaps device work. A pure-Python
fallback keeps the API working without the native build.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Hashable, List, Optional, Sequence, Tuple

from .native import JSCHED_CALLBACK, get_native

__all__ = ["JobGraphExecutor", "execute_plan"]


class JobGraphExecutor:
    """Build a DAG of callables; run() executes them respecting deps with
    ``n_workers`` concurrent workers (native pool when available)."""

    def __init__(self, n_workers: int = 4, use_native: Optional[bool] = None):
        self.n_workers = max(1, n_workers)
        self._jobs: List[Callable[[], None]] = []
        self._deps: List[Tuple[int, int]] = []  # (before, after)
        lib = get_native() if use_native in (None, True) else None
        if use_native is True and lib is None:
            raise RuntimeError("native job scheduler requested but csrc build unavailable")
        self._lib = lib

    def add_job(self, fn: Callable[[], None]) -> int:
        self._jobs.append(fn)
        return len(self._jobs) - 1

    def add_dep(self, before: int, after: int) -> None:
        nj = len(self._jobs)
        if not (0 <= before < nj and 0 <= after < nj) or before == after:
            raise ValueError(f"invalid dependency {before}->{after}")
        self._deps.append((before, after))

    # -- execution --
    def run(self) -> None:
        if self._lib is not None:
            self._run_native()
        else:
            self._run_python()

    def _run_native(self):
        h = self._lib.jsched_new(self.n_workers)
        try:
            for i in range(len(self._jobs)):
                self._lib.jsched_add_job(h, i)
            for before, after in self._deps:
                if self._lib.jsched_add_dep(h, before, after) != 0:
                    raise ValueError(f"bad dependency {before}->{after}")
            errors: List[BaseException] = []

            @JSCHED_CALLBACK
            def cb(job_id, tag, ctx):
                if errors:  # a prior job failed: skip side effects downstream
                    return
                try:
                    self._jobs[job_id]()
                except BaseException as e:  # keep the pool alive; re-raise after
                    errors.append(e)

            rc = self._lib.jsched_run(h, cb, None)
            if errors:
                raise errors[0]
            if rc != 0:
                raise RuntimeError("job graph has a dependency cycle")
        finally:
            self._lib.jsched_free(h)

    def _run_python(self):
        n = len(self._jobs)
        pending = [0] * n
        dependents: List[List[int]] = [[] for _ in range(n)]
        for before, after in self._deps:
            pending[after] += 1
            dependents[before].append(after)
        from collections import deque

        ready = deque(i for i in range(n) if pending[i] == 0)
        done = [0]
        active = [0]
        lock = threading.Lock()
        errors: List[BaseException] = []
        finished = threading.Event()
        if n == 0:
            return

        def worker():
            while not finished.is_set():
                # claim-or-diagnose atomically (mirrors the C++ pool's
                # pop + running++ under one mutex; avoids a spurious
                # cycle report while a peer holds an unclaimed job)
                with lock:
                    if ready:
                        i = ready.popleft()
                        active[0] += 1
                    elif active[0] == 0 and done[0] < n:
                        finished.set()  # true deadlock: nothing runnable or running
                        return
                    else:
                        i = None
                if i is None:
                    time.sleep(0.002)
                    continue
                try:
                    self._jobs[i]()
                except BaseException as e:
                    with lock:
                        errors.append(e)
                    finished.set()
                    return
                with lock:
                    active[0] -= 1
                    done[0] += 1
                    for d in dependents[i]:
                        pending[d] -= 1
                        if pending[d] == 0:
                            ready.append(d)
                    if done[0] == n:
                        finished.set()

        threads = [threading.Thread(target=worker, daemon=True) for _ in range(self.n_workers)]
        for t in threads:
            t.start()
        # no overall timeout: cycle detection and error propagation both set
        # `finished`, and jobs may legitimately run for hours
        finished.wait()
        for t in threads:
            t.join(timeout=5)
        if errors:
            raise errors[0]
        if done[0] != n:
            raise RuntimeError("job graph has a dependency cycle")


def execute_plan(plan, handlers: Dict[str, Callable], n_workers: int = 4,
                 use_native: Optional[bool] = None) -> None:
    """Execute a pipeline Plan (distributed.pipeline_schedules.Plan) over
    callables per job type: handlers[type](stage_id, micro_batch_id,
    chunk_id). Builds the cross-rank dependency DAG (same rules the
    schedule simulator validates) and runs it on the worker pool — the
    host-driven Plan/Job execution path."""
    from ..distributed.pipeline_schedules import (BACKWARD, BACKWARD_B, BACKWARD_W,
                                                  FORWARD, OPT)

    ex = JobGraphExecutor(n_workers=n_workers, use_native=use_native)
    n_stages, n_chunks = plan.n_stages, plan.n_chunks
    total_v = n_stages * n_chunks

    def vstage(rank, chunk):
        return chunk * n_stages + rank

    ids: Dict[Tuple, int] = {}
    for rank in range(n_stages):
        prev = None
        for job in plan.rank_jobs(rank):
            fn = handlers.get(job.type)
            if fn is None:
                continue
            jid = ex.add_job(lambda f=fn, j=job: f(j.stage_id, j.micro_batch_id, j.chunk_id))
            ids[(job.type, vstage(rank, job.chunk_id), job.micro_batch_id)] = jid
            if prev is not None:
                ex.add_dep(prev, jid)  # per-rank program order
            prev = jid
    # cross-rank data deps
    for (typ, vs, m), jid in ids.items():
        if typ == FORWARD and vs > 0:
            dep = ids.get((FORWARD, vs - 1, m))
            if dep is not None:
                ex.add_dep(dep, jid)
        elif typ in (BACKWARD, BACKWARD_B):
            dep = ids.get((FORWARD, total_v - 1, m))
            if dep is not None:
                ex.add_dep(dep, jid)
            if vs < total_v - 1:
                for t in (BACKWARD, BACKWARD_B):
                    dep = ids.get((t, vs + 1, m))
                    if dep is not None:
                        ex.add_dep(dep, jid)
        elif typ == BACKWARD_W:
            dep = ids.get((BACKWARD_B, vs, m))
            if dep is not None:
                ex.add_dep(dep, jid)
    ex.run()
