"""paddle_tpu.hapi — high-level training API (reference python/paddle/hapi)."""

from . import callbacks
from .callbacks import Callback, EarlyStopping, LRScheduler, ModelCheckpoint, ProgBarLogger
from .model import Model
from .model_summary import summary

__all__ = ["Model", "summary", "callbacks"]
