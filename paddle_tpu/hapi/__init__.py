"""paddle_tpu.hapi — high-level training API (reference python/paddle/hapi)."""

from . import callbacks
from .callbacks import Callback, EarlyStopping, LRScheduler, ModelCheckpoint, ProgBarLogger
from .model import Model
from .model_summary import summary

__all__ = ["Model", "summary", "callbacks"]


def __getattr__(name):
    # fault-tolerance callbacks live in their own package (which imports
    # hapi.callbacks) — lazy re-export avoids the circular import while
    # keeping the discoverable `hapi.FaultTolerantCheckpoint` spelling.
    if name in ("FaultTolerantCheckpoint", "LossSpikeSentinel"):
        from .. import fault_tolerance

        return getattr(fault_tolerance, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
