"""hapi Model: prepare/fit/evaluate/predict high-level loop.

Parity: python/paddle/hapi/model.py (Model:325 — train_batch:713,
eval_batch, predict_batch, save/load:1196, fit:1472, evaluate:2200,
predict, summary). TPU design: dygraph adapter only (dygraph is the
default and only eager mode here); the static-graph adapter's role is
covered by jit.to_static on the train step.
"""

from __future__ import annotations

import os
import pickle
from typing import List, Optional, Sequence

import numpy as np

from ..core.tensor import Tensor
from ..io.dataloader import DataLoader
from ..metric import Metric
from ..nn.layer import Layer
from ..observability.recompile import entrypoint as _entrypoint
from ..ops.dispatch import ensure_tensor
from .callbacks import config_callbacks

__all__ = ["Model"]


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Model:
    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self.stop_training = False
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._input_spec = inputs
        self._label_spec = labels
        # fault tolerance: update gate (LossSpikeSentinel) + resume meta
        # (fit(resume_from=...) / FaultTolerantCheckpoint)
        self._update_filter = None
        self._resume_state = None

    # -- configuration -----------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        ms = _to_list(metrics)
        for m in ms:
            if not isinstance(m, Metric):
                raise TypeError(f"metric must be paddle.metric.Metric, got {type(m)}")
        self._metrics = ms

    # -- single-batch ops (reference train_batch:713) ----------------------
    def train_batch(self, inputs, labels=None, update: bool = True):
        self.network.train()
        # recompile-monitor attribution: the step's op compiles (or the
        # jitted step program, if the network is to_static) charge here;
        # compiles after the first completed batch — e.g. a drop_last=False
        # partial final batch — are surfaced as retraces
        with _entrypoint("hapi.Model.train_batch"):
            inputs = [ensure_tensor(x) for x in _to_list(inputs)]
            labels = [ensure_tensor(y) for y in _to_list(labels)]
            outputs = self.network(*inputs)
            outs = _to_list(outputs)
            losses = self._compute_loss(outs, labels)
            total = losses[0]
            for l in losses[1:]:
                total = total + l
            total.backward()
            loss_vals = [float(l.numpy()) for l in losses]
            if update and self._update_filter is not None \
                    and not self._update_filter(loss_vals):
                # sentinel veto: drop the poisoned gradients, keep weights
                self._optimizer.clear_grad()
                update = False
            if update:
                self._optimizer.step()
                self._optimizer.clear_grad()
            metrics = self._update_metrics(outs, labels)
        return (loss_vals, metrics) if metrics else loss_vals

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        import paddle_tpu as paddle

        with paddle.no_grad(), _entrypoint("hapi.Model.eval_batch"):
            inputs = [ensure_tensor(x) for x in _to_list(inputs)]
            labels = [ensure_tensor(y) for y in _to_list(labels)]
            outs = _to_list(self.network(*inputs))
            losses = self._compute_loss(outs, labels) if self._loss else []
            metrics = self._update_metrics(outs, labels)
        loss_vals = [float(l.numpy()) for l in losses]
        return (loss_vals, metrics) if metrics else loss_vals

    def predict_batch(self, inputs):
        self.network.eval()
        import paddle_tpu as paddle

        with paddle.no_grad():
            inputs = [ensure_tensor(x) for x in _to_list(inputs)]
            outs = _to_list(self.network(*inputs))
        return [o.numpy() for o in outs]

    def _compute_loss(self, outs, labels):
        if self._loss is None:
            # network returns loss directly
            return [outs[0]]
        res = self._loss(*(outs + labels))
        return _to_list(res)

    def _update_metrics(self, outs, labels):
        vals = {}
        for m in self._metrics:
            if hasattr(m, "compute"):
                pred = m.compute(*(outs + labels))
                m.update(*[np.asarray(p.numpy() if isinstance(p, Tensor) else p)
                           for p in _to_list(pred)])
            else:
                m.update(*[np.asarray(t.numpy()) for t in outs + labels])
            vals[m.name() if callable(getattr(m, "name", None)) else str(m)] = m.accumulate()
        return vals

    # -- loops -------------------------------------------------------------
    def _make_loader(self, data, batch_size, shuffle, num_workers):
        if data is None or isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          num_workers=num_workers)

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None, resume_from=None):
        """``resume_from``: a committed fault-tolerance checkpoint dir
        (or a root of ``step_*`` dirs — the newest committed one is
        resolved via ``latest_checkpoint``). Weights/optimizer/LR state
        are restored, then the loop fast-forwards to the saved position:
        the resume epoch's shuffle permutation is re-drawn from the
        saved epoch-begin RNG state, already-trained batches are
        skipped without callbacks, and the exact step-boundary RNG
        states are restored — a killed-and-resumed run retraces the
        uninterrupted run step for step (bit-identical weights)."""
        assert train_data is not None, "train_data must be given"
        resume = self._load_resume_state(resume_from) if resume_from else None
        loader = self._make_loader(train_data, batch_size, shuffle, num_workers)
        eval_loader = self._make_loader(eval_data, batch_size, False, num_workers)
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        cbks = config_callbacks(
            callbacks, model=self, batch_size=batch_size, epochs=epochs,
            steps=steps, log_freq=log_freq, verbose=verbose,
            save_freq=save_freq, save_dir=save_dir, metrics=self._metrics)

        self.stop_training = False
        cbks.on_train_begin()
        it = int(resume["global_step"]) if resume else 0
        resume_epoch = int(resume.get("epoch", -1)) if resume else -1
        resume_step = int(resume.get("step_in_epoch", -1)) if resume else -1
        for epoch in range(epochs):
            if self.stop_training:
                break
            replay = (resume is not None and epoch == resume_epoch
                      and resume_step >= 0)
            if resume is not None and epoch < resume_epoch:
                continue  # whole epoch already trained before the kill
            if replay:
                # the epoch's shuffle permutation must come out identical
                # to the killed run's: rewind RNG to its epoch begin
                from ..fault_tolerance.callback import restore_rng_state

                restore_rng_state(resume.get("rng_epoch_begin"))
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, batch in enumerate(loader):
                if replay and step <= resume_step:
                    if step == resume_step:
                        # fast-forward complete: continue with the exact
                        # RNG the killed run had at this step boundary
                        from ..fault_tolerance.callback import \
                            restore_rng_state

                        restore_rng_state(resume.get("rng"))
                    continue
                cbks.on_train_batch_begin(step)
                ins, labs = self._split_batch(batch)
                update = (step + 1) % accumulate_grad_batches == 0
                res = self.train_batch(ins, labs, update=update)
                logs = self._result_logs(res)
                cbks.on_train_batch_end(step, logs)
                it += 1
                if self.stop_training:
                    # a callback (preemption save, sentinel) asked to stop
                    # at this step boundary — don't finish the epoch first
                    break
                if num_iters is not None and it >= num_iters:
                    self.stop_training = True
                    break
            cbks.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_loader, batch_size=batch_size, verbose=verbose,
                              num_workers=num_workers, callbacks=cbks,
                              _inner=True)
        cbks.on_train_end(logs)
        self._resume_state = None

    def _load_resume_state(self, resume_from: str) -> dict:
        """Restore network/optimizer from a committed checkpoint and
        return the train meta (step counters + RNG states) for the
        fast-forward. Accepts a checkpoint dir or a root of them."""
        from ..distributed.checkpoint.atomic import is_committed
        from ..fault_tolerance.checkpointer import (latest_checkpoint,
                                                    restore_train_state)

        path = resume_from
        if not is_committed(path):
            resolved = latest_checkpoint(path)
            if resolved is None:
                raise FileNotFoundError(
                    f"resume_from={resume_from!r}: no committed checkpoint "
                    f"found (is the path a checkpoint dir or a root of "
                    f"step_* dirs?)")
            path = resolved
        meta = restore_train_state(path, self, cause="resume") or {}
        meta.setdefault("global_step", 0)
        self._resume_state = meta
        return meta

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None, _inner=False):
        loader = self._make_loader(eval_data, batch_size, False, num_workers)
        cbks = callbacks if _inner else config_callbacks(
            callbacks, model=self, batch_size=batch_size, verbose=verbose,
            metrics=self._metrics, mode="eval")
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin()
        logs = {}
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            ins, labs = self._split_batch(batch)
            res = self.eval_batch(ins, labs)
            logs = self._result_logs(res, prefix="eval_")
            cbks.on_eval_batch_end(step, logs)
            if num_iters is not None and step + 1 >= num_iters:
                break
        cbks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                callbacks=None, verbose=1):
        loader = self._make_loader(test_data, batch_size, False, num_workers)
        cbks = config_callbacks(callbacks, model=self, batch_size=batch_size,
                                verbose=verbose, mode="predict")
        cbks.on_predict_begin()
        outputs = []
        for step, batch in enumerate(loader):
            cbks.on_predict_batch_begin(step)
            ins, _ = self._split_batch(batch, has_labels=False)
            outs = self.predict_batch(ins)
            outputs.append(outs)
            cbks.on_predict_batch_end(step)
        cbks.on_predict_end()
        # transpose: list over steps of list over outputs -> list over outputs
        n_out = len(outputs[0]) if outputs else 0
        result = [[o[i] for o in outputs] for i in range(n_out)]
        if stack_outputs:
            result = [np.concatenate(r, axis=0) for r in result]
        return result

    def _forward_arity(self) -> Optional[int]:
        """Positional inputs network.forward accepts (the reference uses the
        inputs spec for this; without one, the forward signature decides)."""
        import inspect

        try:
            sig = inspect.signature(self.network.forward)
        except (TypeError, ValueError):
            return None
        n, variadic = 0, False
        for p in sig.parameters.values():
            if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
                n += 1
            elif p.kind == p.VAR_POSITIONAL:
                variadic = True
        return None if variadic else n

    def _split_batch(self, batch, has_labels=True):
        if isinstance(batch, (list, tuple)):
            batch = list(batch)
            if len(batch) == 1:
                return batch, []
            n_in = self._forward_arity()
            if n_in is not None and 0 < n_in < len(batch):
                return batch[:n_in], batch[n_in:] if has_labels else []
            if has_labels:
                return batch[:-1], batch[-1:]
            return batch, []
        return [batch], []

    def _result_logs(self, res, prefix=""):
        logs = {}
        if isinstance(res, tuple):
            losses, metrics = res
            logs.update({f"{prefix}loss": losses})
            for k, v in metrics.items():
                logs[f"{prefix}{k}"] = v
        else:
            logs[f"{prefix}loss"] = res
        return logs

    # -- persistence (reference save:1196 / load) --------------------------
    def save(self, path: str, training: bool = True):
        """training=True: checkpoint (params + optimizer state);
        training=False: export an inference program via jit.save (reference:
        hapi Model.save -> paddle.jit.save when training=False)."""
        dirname = os.path.dirname(path)
        if dirname:
            os.makedirs(dirname, exist_ok=True)
        import paddle_tpu as paddle

        if not training:
            if not self._input_spec:
                raise ValueError(
                    "Model.save(training=False) needs input specs: construct "
                    "Model(net, inputs=[InputSpec(...)]) to export an inference model")
            paddle.jit.save(self.network, path, input_spec=_to_list(self._input_spec))
            return
        paddle.save(self.network.state_dict(), path + ".pdparams")
        if self._optimizer is not None:
            state = getattr(self._optimizer, "state_dict", lambda: {})()
            paddle.save(state, path + ".pdopt")

    def load(self, path: str, skip_mismatch=False, reset_optimizer=False):
        import paddle_tpu as paddle

        params = paddle.load(path + ".pdparams")
        self.network.set_state_dict(params)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and os.path.exists(opt_path):
            state = paddle.load(opt_path)
            if hasattr(self._optimizer, "set_state_dict"):
                self._optimizer.set_state_dict(state)

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary

        return summary(self.network, input_size, dtypes=dtype)
