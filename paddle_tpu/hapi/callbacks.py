"""hapi callbacks (reference python/paddle/hapi/callbacks.py:
Callback base, ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler,
config_callbacks assembly)."""

from __future__ import annotations

import numbers
import os
import time
from typing import List, Optional

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks: List[Callback]):
        self.callbacks = callbacks

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Per-epoch progress logging (reference ProgBarLogger; plain-line
    output rather than a terminal progress bar — log-file friendly)."""

    def __init__(self, log_freq: int = 1, verbose: int = 2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def _fmt(self, logs):
        out = []
        for k, v in (logs or {}).items():
            if isinstance(v, numbers.Number):
                out.append(f"{k}: {v:.4f}")
            elif isinstance(v, (list, tuple, np.ndarray)) and len(np.ravel(v)):
                out.append(f"{k}: {float(np.ravel(v)[0]):.4f}")
        return " - ".join(out)

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self._t0 = time.time()

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        if self.verbose and self.epochs:
            print(f"Epoch {epoch + 1}/{self.epochs}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and step % self.log_freq == 0:
            total = f"/{self.steps}" if self.steps else ""
            print(f"step {step + 1}{total} - {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            print(f"Epoch {epoch + 1} done - {self._fmt(logs)}")

    def on_eval_begin(self, logs=None):
        if self.verbose:
            print("Eval begin...")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval done - {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    """Per-epoch checkpoint save. ``max_to_keep`` bounds disk use on
    long runs: after each save, epoch saves beyond the newest N are
    deleted (``final``/``best_model`` are never counted or deleted).
    ``None`` (default) keeps everything — the original behavior."""

    def __init__(self, save_freq: int = 1, save_dir: Optional[str] = None,
                 max_to_keep: Optional[int] = None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir
        self.max_to_keep = max_to_keep
        self._saved: List[str] = []

    def on_train_begin(self, logs=None):
        self._saved = []

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, f"{epoch}")
            self.model.save(path)
            self._saved.append(path)
            self._retention_gc()

    def _retention_gc(self):
        if not self.max_to_keep:
            return
        while len(self._saved) > self.max_to_keep:
            old = self._saved.pop(0)
            for suffix in (".pdparams", ".pdopt"):
                try:
                    os.remove(old + suffix)
                except FileNotFoundError:
                    pass
            if os.path.isdir(old):  # committed checkpoint-dir style saves
                import shutil

                shutil.rmtree(old, ignore_errors=True)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.stopped_epoch = 0
        if mode not in ("auto", "min", "max"):
            mode = "auto"
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode

    def on_train_begin(self, logs=None):
        self.wait_epoch = 0
        self.best_value = (self.baseline if self.baseline is not None
                           else (np.inf if self.mode == "min" else -np.inf))
        self.model.stop_training = False

    def on_eval_end(self, logs=None):
        if logs is None or self.monitor not in logs:
            return
        value = logs[self.monitor]
        if isinstance(value, (list, tuple, np.ndarray)):
            value = float(np.ravel(value)[0])
        improved = (value < self.best_value - self.min_delta if self.mode == "min"
                    else value > self.best_value + self.min_delta)
        if improved:
            self.best_value = value
            self.wait_epoch = 0
            if self.save_best_model and self.params.get("save_dir"):
                self.model.save(os.path.join(self.params["save_dir"], "best_model"))
        else:
            self.wait_epoch += 1
        if self.wait_epoch > self.patience:
            self.model.stop_training = True
            if self.verbose:
                print(f"Early stopping: {self.monitor} did not improve for "
                      f"{self.patience + 1} evals (best {self.best_value:.5f})")


class TelemetryCallback(Callback):
    """Per-step training telemetry into ``paddle_tpu.observability``:
    one StepTelemetry record per train batch (step wall time, ips from
    the batch size, device-memory watermarks, compile-count delta) —
    surfaced by ``observability.snapshot()["steps"]`` and, when a JSONL
    path is given (argument or ``PADDLE_TPU_TELEMETRY_JSONL``), appended
    one line per step. Added by default in ``config_callbacks`` (cost:
    a clock read + a memory_stats call per batch)."""

    def __init__(self, jsonl_path: Optional[str] = None,
                 entry: str = "hapi.fit", record_memory: bool = True):
        super().__init__()
        self.jsonl_path = jsonl_path or os.environ.get(
            "PADDLE_TPU_TELEMETRY_JSONL") or None
        self.entry = entry
        self.record_memory = record_memory
        self._st = None

    def on_train_begin(self, logs=None):
        from ..observability import StepTelemetry

        self._st = StepTelemetry(entry=self.entry,
                                 jsonl_path=self.jsonl_path,
                                 record_memory=self.record_memory)
        self._st.mark()

    def on_epoch_begin(self, epoch, logs=None):
        if self._st is not None:
            self._st.mark()  # exclude between-epoch work (eval, ckpt)

    def on_train_batch_end(self, step, logs=None):
        if self._st is None:
            return
        extra = {}
        loss = (logs or {}).get("loss")
        if isinstance(loss, (list, tuple)) and loss:
            loss = loss[0]
        if isinstance(loss, numbers.Number):
            extra["loss"] = float(loss)
        self._st.step(num_samples=self.params.get("batch_size"),
                      extra=extra or None)

    def on_train_end(self, logs=None):
        if self._st is not None:
            self._st.close()
            self._st = None


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (reference hapi LRScheduler)."""

    def __init__(self, by_step: bool = True, by_epoch: bool = False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        from ..optimizer.lr import LRScheduler as Sched

        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s is not None:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s is not None:
                s.step()


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train") -> CallbackList:
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    if mode == "train" and not any(isinstance(c, TelemetryCallback) for c in cbks):
        cbks = cbks + [TelemetryCallback()]
    if not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({
        "batch_size": batch_size, "epochs": epochs, "steps": steps,
        "verbose": verbose, "metrics": metrics or [], "save_dir": save_dir,
    })
    return lst
