"""paddle.summary (reference python/paddle/hapi/model_summary.py):
layer-by-layer table of output shapes + parameter counts via forward hooks.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer import Layer

__all__ = ["summary"]


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    """Returns {'total_params': int, 'trainable_params': int} and prints the
    per-layer table (reference summary contract)."""
    import paddle_tpu as paddle

    if input is None:
        assert input_size is not None, "input_size or input required"
        sizes = input_size if isinstance(input_size, list) else [input_size]
        sizes = [s if isinstance(s, (list, tuple)) else (s,) for s in sizes]
        dts = dtypes if isinstance(dtypes, (list, tuple)) else [dtypes] * len(sizes)
        inputs = [
            paddle.to_tensor(np.ones([d if d and d > 0 else 1 for d in s],
                                     dtype=dt or "float32"))
            for s, dt in zip(sizes, dts)
        ]
    else:
        inputs = input if isinstance(input, (list, tuple)) else [input]

    rows = []
    hooks = []

    def make_hook(name, layer):
        def hook(l, ins, outs):
            out = outs[0] if isinstance(outs, (list, tuple)) else outs
            shape = list(out.shape) if isinstance(out, Tensor) else "?"
            n_params = sum(int(np.prod(p.shape)) for p in l.parameters(include_sublayers=False))
            rows.append((f"{type(l).__name__}-{len(rows)}", shape, n_params))
        return hook

    for name, sub in net.named_sublayers(include_self=False):
        if not list(sub.named_children()):  # leaves only
            hooks.append(sub.register_forward_post_hook(make_hook(name, sub)))

    was_training = net.training
    net.eval()
    try:
        with paddle.no_grad():
            net(*inputs)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)

    width = 76
    print("-" * width)
    print(f"{'Layer (type)':<30}{'Output Shape':<28}{'Param #':>12}")
    print("=" * width)
    for name, shape, n in rows:
        print(f"{name:<30}{str(shape):<28}{n:>12,}")
    print("=" * width)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print("-" * width)
    return {"total_params": total, "trainable_params": trainable}
