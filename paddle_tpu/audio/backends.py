"""Audio IO (parity: python/paddle/audio/backends/ — wave_backend.load/save).

Pure-stdlib WAV codec (the reference's default backend is also a
soundfile/wave wrapper); covers PCM16/PCM8/float32 mono+stereo.
"""

from __future__ import annotations

import wave
from typing import Tuple

import numpy as np

from ..core.tensor import Tensor

__all__ = ["load", "save", "info"]


def load(filepath: str, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True) -> Tuple[Tensor, int]:
    with wave.open(filepath, "rb") as w:
        sr = w.getframerate()
        n_channels = w.getnchannels()
        sampwidth = w.getsampwidth()
        w.setpos(frame_offset)
        n = w.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = w.readframes(n)
    if sampwidth == 2:
        data = np.frombuffer(raw, "<i2").astype(np.float32)
        if normalize:
            data /= 32768.0
    elif sampwidth == 1:
        data = (np.frombuffer(raw, np.uint8).astype(np.float32) - 128.0)
        if normalize:
            data /= 128.0
    elif sampwidth == 4:
        data = np.frombuffer(raw, "<i4").astype(np.float32)
        if normalize:
            data /= 2147483648.0
    else:
        raise ValueError(f"unsupported sample width {sampwidth}")
    data = data.reshape(-1, n_channels)
    if channels_first:
        data = data.T
    return Tensor(data), sr


def save(filepath: str, src: Tensor, sample_rate: int, channels_first: bool = True,
         encoding: str = "PCM_16", bits_per_sample: int = 16) -> None:
    data = np.asarray(src._data if isinstance(src, Tensor) else src, np.float32)
    if data.ndim == 1:
        data = data[None, :] if channels_first else data[:, None]
    if channels_first:
        data = data.T  # -> [frames, channels]
    pcm = np.clip(data, -1.0, 1.0)
    pcm16 = (pcm * 32767.0).astype("<i2")
    with wave.open(filepath, "wb") as w:
        w.setnchannels(data.shape[1])
        w.setsampwidth(2)
        w.setframerate(sample_rate)
        w.writeframes(pcm16.tobytes())


def info(filepath: str):
    with wave.open(filepath, "rb") as w:
        class _Info:
            sample_rate = w.getframerate()
            num_channels = w.getnchannels()
            num_frames = w.getnframes()
            bits_per_sample = w.getsampwidth() * 8

        return _Info()
