"""paddle.audio equivalent — features, functional, IO backends.

Parity: python/paddle/audio/ (features/layers.py, functional/, backends/).
"""

from . import backends, features, functional
from .backends import info, load, save
from .features import LogMelSpectrogram, MFCC, MelSpectrogram, Spectrogram

__all__ = ["features", "functional", "backends", "load", "save", "info",
           "Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]
