"""Audio functional ops (parity: python/paddle/audio/functional/ —
window functions, mel scale conversion, fbank matrix, dct matrix).

All pure jnp; the STFT inside Spectrogram is framing + rfft, which XLA
maps onto batched matmuls/FFT on TPU.
"""

from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "create_dct", "get_window", "power_to_db",
           "mel_projection", "mfcc_dct"]


def hz_to_mel(freq, htk: bool = False):
    scalar = not isinstance(freq, (Tensor, np.ndarray, jnp.ndarray))
    f = freq._data if isinstance(freq, Tensor) else jnp.asarray(freq, jnp.float32)
    if htk:
        out = 2595.0 * jnp.log10(1.0 + f / 700.0)
    else:
        # Slaney scale
        f_min, f_sp = 0.0, 200.0 / 3
        mels = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = jnp.where(f >= min_log_hz,
                        min_log_mel + jnp.log(jnp.maximum(f, 1e-10) / min_log_hz) / logstep,
                        mels)
    if scalar:
        return float(out)
    return Tensor(out) if isinstance(freq, Tensor) else out


def mel_to_hz(mel, htk: bool = False):
    scalar = not isinstance(mel, (Tensor, np.ndarray, jnp.ndarray))
    m = mel._data if isinstance(mel, Tensor) else jnp.asarray(mel, jnp.float32)
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        freqs = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = jnp.where(m >= min_log_mel,
                        min_log_hz * jnp.exp(logstep * (m - min_log_mel)),
                        freqs)
    if scalar:
        return float(out)
    return Tensor(out) if isinstance(mel, Tensor) else out


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0, f_max: float = 11025.0,
                    htk: bool = False):
    lo = hz_to_mel(float(f_min), htk)
    hi = hz_to_mel(float(f_max), htk)
    mels = jnp.linspace(lo, hi, n_mels)
    return mel_to_hz(mels, htk)


def fft_frequencies(sr: int, n_fft: int):
    return jnp.linspace(0, float(sr) / 2, 1 + n_fft // 2)


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64, f_min: float = 0.0,
                         f_max: Optional[float] = None, htk: bool = False,
                         norm: str = "slaney"):
    """[n_mels, 1 + n_fft//2] triangular mel filterbank."""
    if f_max is None:
        f_max = float(sr) / 2
    fftfreqs = fft_frequencies(sr, n_fft)
    melfreqs = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = jnp.diff(melfreqs)
    ramps = melfreqs[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0.0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (melfreqs[2: n_mels + 2] - melfreqs[:n_mels])
        weights = weights * enorm[:, None]
    return weights


def create_dct(n_mfcc: int, n_mels: int, norm: Optional[str] = "ortho"):
    """[n_mels, n_mfcc] DCT-II matrix (parity: audio/functional/create_dct)."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k[None, :]) * 2.0
    if norm == "ortho":
        dct = dct.at[:, 0].multiply(1.0 / math.sqrt(2))
        dct = dct * math.sqrt(1.0 / (2.0 * n_mels))
    return dct


def get_window(window: str, win_length: int, fftbins: bool = True):
    n = win_length
    denom = n if fftbins else n - 1
    t = jnp.arange(n, dtype=jnp.float32)
    if window in ("hann", "hanning"):
        return 0.5 - 0.5 * jnp.cos(2 * math.pi * t / denom)
    if window in ("hamming",):
        return 0.54 - 0.46 * jnp.cos(2 * math.pi * t / denom)
    if window in ("blackman",):
        return (0.42 - 0.5 * jnp.cos(2 * math.pi * t / denom)
                + 0.08 * jnp.cos(4 * math.pi * t / denom))
    if window in ("rectangular", "boxcar", "ones"):
        return jnp.ones(n, jnp.float32)
    raise ValueError(f"unsupported window {window!r}")


def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                top_db: Optional[float] = 80.0):
    """Power spectrogram -> dB (reference paddle.audio.functional
    power_to_db). Dispatches as an op so the schema sweep covers it."""
    from ..ops.dispatch import apply_op, ensure_tensor

    is_t = isinstance(spect, Tensor)

    def fn(d):
        log_spec = 10.0 * jnp.log10(jnp.maximum(d, amin))
        log_spec = log_spec - 10.0 * math.log10(max(ref_value, amin))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
        return log_spec

    out = apply_op("power_to_db", fn, ensure_tensor(spect))
    return out if is_t else out._data


def mel_projection(spec, fbank_matrix):
    """[..., freq, time] power spectrogram x [n_mels, freq] filter bank
    -> [..., n_mels, time] (the projection stage of MelSpectrogram)."""
    from ..ops.dispatch import apply_op, ensure_tensor

    def fn(s, fb):
        return jnp.einsum("mf,...ft->...mt", fb, s)

    return apply_op("mel_projection", fn, ensure_tensor(spec),
                    ensure_tensor(fbank_matrix))


def mfcc_dct(logmel, dct_matrix):
    """[..., n_mels, time] log-mel x [n_mels, n_mfcc] DCT basis ->
    [..., n_mfcc, time] (the DCT stage of MFCC)."""
    from ..ops.dispatch import apply_op, ensure_tensor

    def fn(lm, dct):
        return jnp.einsum("mk,...mt->...kt", dct, lm)

    return apply_op("mfcc_dct", fn, ensure_tensor(logmel),
                    ensure_tensor(dct_matrix))
