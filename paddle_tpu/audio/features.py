"""Audio feature layers (parity: python/paddle/audio/features/layers.py —
Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC).

STFT = strided framing + window + rfft, expressed as one jax function per
layer so XLA fuses the pipeline; the mel projection is a matmul on the MXU.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer import Layer
from ..ops.dispatch import apply_op
from . import functional as AF

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


def _frame(x, frame_length: int, hop_length: int, center: bool, pad_mode: str):
    # x: [..., T] -> [..., n_frames, frame_length]
    if center:
        pad = [(0, 0)] * (x.ndim - 1) + [(frame_length // 2, frame_length // 2)]
        x = jnp.pad(x, pad, mode="reflect" if pad_mode == "reflect" else "constant")
    T = x.shape[-1]
    n_frames = 1 + (T - frame_length) // hop_length
    idx = (jnp.arange(n_frames)[:, None] * hop_length + jnp.arange(frame_length)[None, :])
    return x[..., idx]


class Spectrogram(Layer):
    def __init__(self, n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True, pad_mode: str = "reflect",
                 dtype: str = "float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        w = AF.get_window(window, self.win_length)
        if self.win_length < n_fft:  # center-pad window to n_fft
            lpad = (n_fft - self.win_length) // 2
            w = jnp.pad(w, (lpad, n_fft - self.win_length - lpad))
        self.register_buffer("window", Tensor(w), persistable=False)

    def forward(self, x: Tensor) -> Tensor:
        n_fft, hop, center, pad_mode, power = (self.n_fft, self.hop_length,
                                               self.center, self.pad_mode, self.power)
        win = self.window._data

        def fn(x, win):
            frames = _frame(x, n_fft, hop, center, pad_mode)
            spec = jnp.fft.rfft(frames * win, n=n_fft, axis=-1)
            mag = jnp.abs(spec)
            out = mag if power == 1.0 else mag ** power
            return jnp.swapaxes(out, -1, -2)  # [..., freq, time]

        return apply_op("spectrogram", fn, x, self.window)


class MelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 2048, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None, htk: bool = False,
                 norm: str = "slaney", dtype: str = "float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length, window, power,
                                       center, pad_mode, dtype)
        fbank = AF.compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max, htk, norm)
        self.register_buffer("fbank_matrix", Tensor(fbank), persistable=False)

    def forward(self, x: Tensor) -> Tensor:
        spec = self.spectrogram(x)
        return AF.mel_projection(spec, self.fbank_matrix)


class LogMelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: Optional[float] = None, **mel_kwargs):
        super().__init__()
        self.mel = MelSpectrogram(sr=sr, **mel_kwargs)
        self.ref_value, self.amin, self.top_db = ref_value, amin, top_db

    def forward(self, x: Tensor) -> Tensor:
        mel = self.mel(x)
        return AF.power_to_db(mel, ref_value=self.ref_value, amin=self.amin,
                              top_db=self.top_db)


class MFCC(Layer):
    def __init__(self, sr: int = 22050, n_mfcc: int = 40, norm: str = "ortho", **mel_kwargs):
        super().__init__()
        self.log_mel = LogMelSpectrogram(sr=sr, **mel_kwargs)
        n_mels = mel_kwargs.get("n_mels", 64)
        self.register_buffer("dct_matrix", Tensor(AF.create_dct(n_mfcc, n_mels, norm)),
                             persistable=False)

    def forward(self, x: Tensor) -> Tensor:
        logmel = self.log_mel(x)
        return AF.mfcc_dct(logmel, self.dct_matrix)
