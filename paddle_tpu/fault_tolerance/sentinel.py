"""Loss-spike sentinel: skip poisoned updates, roll back persistent
divergence.

Long runs on real fleets hit loss blow-ups — a bad batch, an overflow,
a flaky host. The sentinel is a hapi callback that watches the per-step
loss with a ROBUST running statistic (median/MAD over a sliding window
— one outlier cannot drag the threshold the way a mean/std would) and
classifies each step:

- ``nan``/``inf``: the loss is not finite (the ``amp/debugging.py``
  numerics check applied to the step loss);
- ``spike``: ``|loss - median| > k * (1.4826 * MAD)`` after warmup.

A bad step's parameter update is SKIPPED — the sentinel registers an
update filter on the model, which ``Model.train_batch`` consults
between ``backward()`` and ``optimizer.step()``, so the poisoned
gradients never touch the weights (up to ``max_skips`` consecutive
times). After ``rollback_after`` consecutive bad steps it rolls the
model+optimizer back to the last committed checkpoint (when given a
checkpoint dir or a ``FaultTolerantCheckpoint`` to resolve one).

Every action is counted: ``paddle_tpu_loss_spike_total{reason}``,
``..._skipped_updates_total``, ``..._rollbacks_total``.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Optional

import numpy as np

from ..hapi.callbacks import Callback
from . import metrics as _fm

__all__ = ["LossSpikeSentinel"]


def _loss_scalar(loss) -> Optional[float]:
    if loss is None:
        return None
    if isinstance(loss, (list, tuple)) and loss:
        loss = loss[0]
    try:
        return float(np.ravel(np.asarray(loss))[0])
    except (TypeError, ValueError):
        return None


class LossSpikeSentinel(Callback):
    """Args:
        k: robust z-score threshold (spike when ``|loss-median|`` exceeds
            ``k`` robust sigmas).
        window: sliding window of GOOD losses the statistic runs over.
        warmup_steps: minimum good samples before spike detection arms
            (NaN/Inf detection is always armed).
        max_skips: consecutive updates to skip before giving up on
            skipping (further bad steps still count toward rollback).
        rollback_after: consecutive bad steps that trigger a rollback.
        checkpoint_dir: where to resolve the rollback checkpoint
            (``latest_checkpoint``); alternatively pass ``checkpoint=``
            a FaultTolerantCheckpoint and its dir is used.
        min_sigma: floor on the robust sigma so a flat loss curve
            (MAD ~ 0) doesn't flag numerical noise as spikes.
    """

    def __init__(self, k: float = 6.0, window: int = 64,
                 warmup_steps: int = 16, max_skips: int = 4,
                 rollback_after: int = 8,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint=None, min_sigma: float = 1e-6,
                 verbose: int = 1):
        super().__init__()
        self.k = float(k)
        self.window = int(window)
        self.warmup_steps = int(warmup_steps)
        self.max_skips = int(max_skips)
        self.rollback_after = int(rollback_after)
        self.checkpoint_dir = checkpoint_dir
        self._ft_checkpoint = checkpoint
        self.min_sigma = float(min_sigma)
        self.verbose = verbose
        self._losses: deque = deque(maxlen=self.window)
        self.consecutive_bad = 0
        self.skipped = 0
        self.rollbacks = 0

    # -- wiring --------------------------------------------------------------
    def set_model(self, model):
        super().set_model(model)
        model._update_filter = self._update_filter

    def on_train_begin(self, logs=None):
        self._losses.clear()
        self.consecutive_bad = 0

    def on_train_end(self, logs=None):
        if getattr(self.model, "_update_filter", None) is self._update_filter:
            self.model._update_filter = None

    # -- classification ------------------------------------------------------
    def _classify(self, loss: float) -> Optional[str]:
        from ..amp.debugging import DebugMode, check_numerics

        if not math.isfinite(loss):
            n_nan, n_inf, _ = check_numerics(
                np.asarray(loss), op_type="train_step_loss",
                var_name="loss", debug_mode=DebugMode.CHECK_ALL)
            return "nan" if int(n_nan.numpy()) else "inf"
        if len(self._losses) >= self.warmup_steps:
            med = float(np.median(self._losses))
            mad = float(np.median(np.abs(np.asarray(self._losses) - med)))
            sigma = max(1.4826 * mad, self.min_sigma)
            if abs(loss - med) > self.k * sigma:
                return "spike"
        return None

    # -- the filter Model.train_batch consults -------------------------------
    def _update_filter(self, loss_vals) -> bool:
        """True: apply the optimizer update. False: skip it."""
        loss = _loss_scalar(loss_vals)
        if loss is None:
            return True
        reason = self._classify(loss)
        if reason is None:
            self.consecutive_bad = 0
            self._losses.append(loss)
            return True
        _fm.loss_spike_total.labels(reason).inc()
        self.consecutive_bad += 1
        if self.verbose:
            print(f"[LossSpikeSentinel] step loss {loss:.6g} flagged "
                  f"({reason}, consecutive {self.consecutive_bad})")
        if self.consecutive_bad >= self.rollback_after:
            if self._rollback():
                return False
        if self.consecutive_bad <= self.max_skips:
            _fm.loss_spike_skipped_updates_total.inc()
            self.skipped += 1
            return False
        # out of skip budget and no rollback target: let training proceed
        # (the run owner sees the counters and the log line)
        return True

    def _rollback(self) -> bool:
        from .checkpointer import latest_checkpoint, restore_train_state

        root = self.checkpoint_dir
        if root is None and self._ft_checkpoint is not None:
            root = self._ft_checkpoint.dir
        if root is None:
            return False
        path = latest_checkpoint(root)
        if path is None:
            return False
        restore_train_state(path, self.model, cause="rollback")
        _fm.loss_spike_rollbacks_total.inc()
        self.rollbacks += 1
        self.consecutive_bad = 0
        self._losses.clear()
        if self.verbose:
            print(f"[LossSpikeSentinel] rolled back to {path}")
        return True
