"""Preemption handling: turn SIGTERM/SIGINT into a cooperative
"checkpoint at the next step boundary, then stop" request.

Preemptible TPU slices get a SIGTERM with a grace window before the
VM disappears. Killing the process mid-step (or worse, mid-save) is
exactly what the atomic protocol defends against — but the graceful
path is better: the signal handler only flips a flag; the training
loop (``FaultTolerantCheckpoint``) polls it at every step boundary,
runs one final SYNCHRONOUS save, and stops cleanly.

The handler is process-global (signals are), idempotent to install,
and restores the previous handlers on uninstall. A second SIGINT
falls through to the previous handler (double ctrl-C still kills an
interactive run). Tests drive it with ``request()`` — no real signal
needed.
"""

from __future__ import annotations

import signal
import threading
import warnings
from typing import Optional, Tuple

from . import metrics as _fm

__all__ = ["PreemptionHandler", "install_preemption_handler",
           "uninstall_preemption_handler", "preemption_requested",
           "clear_preemption", "request_preemption",
           "add_preemption_listener", "remove_preemption_listener"]


def _flight_dump(reason: str):
    """Snapshot the tracing flight recorder on preemption: the grace
    window is the last chance to capture what the serving engine /
    training loop was doing. The write is small (last-N events + state
    providers) and must never turn a graceful preemption into a crash."""
    try:
        from ..observability import tracing

        tracing.flight_dump(reason)
    except Exception:  # noqa: BLE001 — never block the shutdown path
        pass


class PreemptionHandler:
    def __init__(self, signals: Tuple[int, ...] = (signal.SIGTERM,
                                                   signal.SIGINT)):
        self.signals = tuple(signals)
        self._event = threading.Event()
        self._prev = {}
        self._installed = False
        self.last_signal: Optional[int] = None
        # listeners: fn(reason_str) fired when preemption is requested
        # (signal or programmatic). How the serving router turns SIGTERM
        # into a graceful drain instead of a fail-all crash. Each runs
        # try/except — a listener must never break the shutdown path,
        # and anything slow must hop off the signal-handler thread.
        self._listeners: list = []

    def install(self) -> "PreemptionHandler":
        if self._installed:
            return self
        if threading.current_thread() is not threading.main_thread():
            warnings.warn("PreemptionHandler.install: not on the main "
                          "thread; signal handlers not installed "
                          "(request()/polling still works)")
            return self
        for s in self.signals:
            try:
                self._prev[s] = signal.signal(s, self._on_signal)
            except (ValueError, OSError):  # non-main interpreter, etc.
                pass
        self._installed = True
        return self

    def uninstall(self):
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except (ValueError, OSError):
                pass
        self._prev.clear()
        self._installed = False

    def _on_signal(self, signum, frame):
        if signum == signal.SIGINT and self._event.is_set():
            # second ctrl-C: defer to the previous handler (usually
            # KeyboardInterrupt) so an interactive run stays killable
            prev = self._prev.get(signum)
            if callable(prev):
                return prev(signum, frame)
            raise KeyboardInterrupt
        self.last_signal = signum
        try:
            name = signal.Signals(signum).name
        except ValueError:
            name = str(signum)
        _fm.preemptions_total.labels(name).inc()
        self._event.set()
        _flight_dump(f"signal_{name}")
        self._notify(f"signal_{name}")

    def _notify(self, reason: str):
        for fn in list(self._listeners):
            try:
                fn(reason)
            except Exception:  # noqa: BLE001 — never break the shutdown path
                pass

    def add_listener(self, fn):
        if fn not in self._listeners:
            self._listeners.append(fn)

    def remove_listener(self, fn):
        try:
            self._listeners.remove(fn)
        except ValueError:
            pass

    # cooperative surface ----------------------------------------------------
    @property
    def requested(self) -> bool:
        return self._event.is_set()

    def request(self):
        """Programmatic preemption (tests / external orchestrators)."""
        _fm.preemptions_total.labels("manual").inc()
        self._event.set()
        _flight_dump("preemption_requested")
        self._notify("manual")

    def clear(self):
        self._event.clear()
        self.last_signal = None


_handler: Optional[PreemptionHandler] = None
_lock = threading.Lock()


def _ensure_handler(signals=(signal.SIGTERM, signal.SIGINT)
                    ) -> PreemptionHandler:
    global _handler
    with _lock:
        if _handler is None:
            _handler = PreemptionHandler(signals)
        return _handler


def install_preemption_handler(signals=(signal.SIGTERM, signal.SIGINT)
                               ) -> PreemptionHandler:
    """Install (or return) the process-global handler."""
    return _ensure_handler(signals).install()


def uninstall_preemption_handler():
    global _handler
    with _lock:
        if _handler is not None:
            _handler.uninstall()


def preemption_requested() -> bool:
    h = _handler
    return h.requested if h is not None else False


def request_preemption():
    """Flag a preemption without a real signal (tests/orchestrators)."""
    _ensure_handler().request()


def clear_preemption():
    h = _handler
    if h is not None:
        h.clear()


def add_preemption_listener(fn):
    """Register ``fn(reason)`` to fire when preemption is requested
    (SIGTERM/SIGINT or programmatic) — the hook the serving router's
    graceful drain rides. Installs nothing by itself; pair with
    ``install_preemption_handler()`` for real signals."""
    _ensure_handler().add_listener(fn)


def remove_preemption_listener(fn):
    h = _handler
    if h is not None:
        h.remove_listener(fn)
