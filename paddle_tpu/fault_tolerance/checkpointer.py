"""Async checkpointer: millisecond train-thread snapshot, background
commit, bounded queue, retention GC — the CheckFreq split applied to
this repo's atomic checkpoint protocol.

The cost model: a synchronous ``save_state_dict`` holds the train
thread for device->host transfer + pickle + fsync + rename. Of those,
only the device->host snapshot must happen at the step boundary (the
arrays are immutable once fetched — later optimizer steps DONATE the
old device buffers, they never mutate the host copy). So ``save()``
does exactly that on the caller thread (``jax.device_get`` of the
model+optimizer pytree, timed as ``paddle_tpu_checkpoint_snapshot_
seconds`` — the whole train pause), and hands the host pytree to one
background writer thread that serializes, fsyncs and commits through
``distributed.checkpoint.atomic``.

The job queue is BOUNDED (default 2) and ``save()`` blocks when it is
full: if the disk can't keep up with the save cadence, training slows
instead of snapshots piling up in host RAM. ``wait_until_finished()``
drains the queue (call it before reading the checkpoint back or at
train end); background write errors are re-raised there and on the
next ``save()``.

Retention GC after every commit: keep the newest ``max_to_keep``
committed steps, plus every ``keep_every_n_steps``-th step forever
(week-long runs keep sparse history without filling the disk).

Multi-process saves need a barrier inside the commit, which must not
run on a background thread while the train thread races toward the
next collective — the checkpointer forces ``sync`` mode there.
"""

from __future__ import annotations

import os
import pickle
import queue
import shutil
import threading
import time
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..distributed.checkpoint.atomic import (atomic_write, checkpoint_step,
                                             cleanup_stale_tmp, is_committed,
                                             latest_checkpoint)
from ..distributed.checkpoint.load_state_dict import (_read_pickle,
                                                      read_state_dict)
from ..distributed.checkpoint.save_state_dict import write_state_dict_files
from . import metrics as _fm

__all__ = ["AsyncCheckpointer", "snapshot_state_dict", "save_train_state",
           "load_train_state", "restore_train_state", "latest_checkpoint"]

TRAIN_META_FILE = "train_meta.pkl"


def snapshot_state_dict(state_dict) -> Any:
    """Device->host copy of a nested state dict: Tensors/jax arrays
    become numpy (one ``device_get`` per leaf — milliseconds on the
    train thread), everything else passes through. Multi-controller
    arrays that aren't fully addressable stay as jax arrays; their
    local shards are read during the (sync) write instead."""
    from ..core.tensor import Tensor

    def rec(obj):
        if isinstance(obj, Tensor):
            obj = obj._data
        if isinstance(obj, jax.Array):
            if getattr(obj, "is_fully_addressable", True):
                return np.asarray(jax.device_get(obj))
            return obj
        if isinstance(obj, dict):
            return {k: rec(v) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            return type(obj)(rec(v) for v in obj)
        return obj

    return rec(state_dict)


def _nbytes(obj) -> int:
    if isinstance(obj, np.ndarray):
        return obj.nbytes
    if isinstance(obj, dict):
        return sum(_nbytes(v) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return sum(_nbytes(v) for v in obj)
    return 0


class AsyncCheckpointer:
    """Step-addressed checkpoints under ``root`` (``step_{n:08d}/``),
    written through the atomic commit protocol.

    ``save(step, state_dict)``: snapshot now, write in the background.
    ``save(..., sync=True)``: write+commit before returning (the final
    preemption save). ``restore`` / ``latest_step`` resolve committed
    saves only.
    """

    def __init__(self, root: str, max_to_keep: Optional[int] = None,
                 keep_every_n_steps: Optional[int] = None,
                 queue_size: int = 2):
        self.root = os.path.abspath(root)
        self.max_to_keep = max_to_keep
        self.keep_every_n_steps = keep_every_n_steps
        os.makedirs(self.root, exist_ok=True)
        cleanup_stale_tmp(self.root)
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, queue_size))
        self._thread: Optional[threading.Thread] = None
        self._err: Optional[BaseException] = None
        self._lock = threading.Lock()

    # -- paths ---------------------------------------------------------------
    def step_path(self, step: int) -> str:
        return os.path.join(self.root, f"step_{int(step):08d}")

    def latest_path(self) -> Optional[str]:
        return latest_checkpoint(self.root)

    def latest_step(self) -> Optional[int]:
        p = self.latest_path()
        return checkpoint_step(p) if p else None

    # -- save ----------------------------------------------------------------
    def save(self, step: int, state_dict, meta: Optional[dict] = None,
             sync: bool = False):
        """Checkpoint ``state_dict`` (nested dict of Tensors/arrays) as
        ``step``. Returns after the device->host snapshot (async) or
        after the commit (sync). Raises any pending background error."""
        self._raise_pending()
        if jax.process_count() > 1:
            sync = True  # commit barrier cannot run on a bg thread
        t0 = time.perf_counter()
        snap = snapshot_state_dict(state_dict)
        _fm.snapshot_seconds.observe(time.perf_counter() - t0)
        _fm.save_bytes.inc(_nbytes(snap))
        if sync:
            # a sync save (preemption/final) supersedes queued async ones;
            # drain first so two writers never commit the same step dir
            self.wait_until_finished()
            self._write(step, snap, meta, "sync")
            return
        self._ensure_thread()
        t1 = time.perf_counter()
        try:
            self._q.put((step, snap, meta), block=False)
        except queue.Full:
            # bounded queue: block the train thread (and say so in the
            # metrics) rather than buffering unbounded snapshots
            self._q.put((step, snap, meta))
            _fm.queue_blocked_seconds.observe(time.perf_counter() - t1)

    def wait_until_finished(self):
        """Block until every queued save has committed; re-raise the
        first background error if one occurred."""
        self._q.join()
        self._raise_pending()

    def close(self):
        """Drain and stop the writer thread (idempotent)."""
        self.wait_until_finished()
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None and t.is_alive():
            self._q.put(None)
            t.join(timeout=30)

    # -- restore -------------------------------------------------------------
    def restore(self, step: Optional[int] = None):
        """(state_dict, meta) of ``step`` (default: newest committed).
        Returns (None, None) when nothing committed exists."""
        path = self.step_path(step) if step is not None else self.latest_path()
        if path is None or not is_committed(path):
            return None, None
        return load_train_state(path)

    # -- internals -----------------------------------------------------------
    def _ensure_thread(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._worker, name="paddle-tpu-checkpointer",
                    daemon=True)
                self._thread.start()

    def _worker(self):
        while True:
            job = self._q.get()
            if job is None:
                self._q.task_done()
                return
            try:
                self._write(*job, "async")
            except BaseException as e:  # surfaced on next save()/wait
                self._err = e
                _fm.save_errors_total.inc()
            finally:
                self._q.task_done()

    def _write(self, step: int, snap, meta: Optional[dict], mode: str):
        t0 = time.perf_counter()
        save_train_state(self.step_path(step), snap, meta,
                         extra_marker={"step": int(step)})
        _fm.save_seconds.observe(time.perf_counter() - t0)
        _fm.saves_total.labels(mode).inc()
        self._gc()

    def _gc(self):
        keep_n = self.max_to_keep
        if keep_n is None:
            return
        steps = []
        for name in os.listdir(self.root):
            p = os.path.join(self.root, name)
            if ".tmp-" in name or ".old-" in name or not os.path.isdir(p):
                continue
            s = checkpoint_step(p)
            if s is not None and is_committed(p):
                steps.append((s, p))
        steps.sort(reverse=True)
        for s, p in steps[keep_n:]:
            if self.keep_every_n_steps and s and \
                    s % self.keep_every_n_steps == 0:
                continue  # sparse permanent history
            shutil.rmtree(p, ignore_errors=True)
            _fm.gc_deleted_total.inc()

    def _raise_pending(self):
        err, self._err = self._err, None
        if err is not None:
            raise RuntimeError(
                "background checkpoint save failed") from err


# ---------------------------------------------------------------------------
# Train-state files: the sharded tensor state + one pickled meta record
# (step counters, RNG states) committed together in one atomic dir.
# ---------------------------------------------------------------------------

def save_train_state(path: str, state_dict, meta: Optional[dict] = None,
                     extra_marker: Optional[dict] = None):
    """One committed checkpoint dir holding ``state_dict`` (tensor
    state, via the sharded writer) plus ``train_meta.pkl`` — both
    covered by the COMMITTED digests."""
    if jax.process_count() > 1:
        # the sharded saver owns the barrier/commit dance; meta rides
        # along by being written before the commit barrier
        from ..distributed.collective import barrier
        from ..distributed.checkpoint.atomic import commit_dir

        with atomic_write(path, shared_tmp=True) as tmp:
            write_state_dict_files(state_dict, tmp)
            if jax.process_index() == 0 and meta is not None:
                with open(os.path.join(tmp, TRAIN_META_FILE), "wb") as f:
                    pickle.dump(meta, f, protocol=4)
        barrier()
        if jax.process_index() == 0:
            commit_dir(tmp, os.path.abspath(path), extra_marker)
        barrier()
        return
    with atomic_write(path, extra_marker=extra_marker) as tmp:
        write_state_dict_files(state_dict, tmp)
        if meta is not None:
            with open(os.path.join(tmp, TRAIN_META_FILE), "wb") as f:
                pickle.dump(meta, f, protocol=4)


def load_train_state(path: str):
    """(state_dict, meta) from a committed checkpoint dir; digests are
    verified, corruption raises ``CheckpointCorruptError``."""
    state = read_state_dict(path)
    meta = None
    if os.path.exists(os.path.join(path, TRAIN_META_FILE)):
        meta = _read_pickle(path, TRAIN_META_FILE)
    return state, meta


# Optimizer accumulators are keyed by ``p.name`` — "generated_tensor_N"
# names minted by a process-global counter, so they differ between the
# saving process and any restoring model instance. FT checkpoints
# therefore store optimizer state keyed by the parameter's STRUCTURED
# name (the model state_dict key, stable across restarts), translated
# back to the live optimizer's p.names at restore.
_SEP = "::"


def export_optimizer_state(model) -> Dict[str, Any]:
    opt = model._optimizer
    state = opt.state_dict()
    smap = {id(p): n for n, p in model.network.state_dict().items()}
    params = sorted(getattr(opt, "_parameter_list", []),
                    key=lambda p: -len(p.name))
    out = {}
    for k, v in state.items():
        for p in params:
            if k.startswith(p.name + "_") and id(p) in smap:
                out[f"{smap[id(p)]}{_SEP}{k[len(p.name) + 1:]}"] = v
                break
        else:
            out[k] = v  # @step, LR_Scheduler, unmatched extras
    return out


def import_optimizer_state(model, saved: Dict[str, Any]):
    opt = model._optimizer
    smap = {n: p for n, p in model.network.state_dict().items()}
    state = {}
    for k, v in saved.items():
        if _SEP in k:
            sname, acc = k.rsplit(_SEP, 1)
            p = smap.get(sname)
            if p is not None:
                state[f"{p.name}_{acc}"] = v
                continue
        state[k] = v
    opt.set_state_dict(state)


def restore_train_state(path: str, model, cause: str = "resume"):
    """Restore a ``hapi.Model``'s network + optimizer from a committed
    train-state checkpoint; returns the train meta (step counters, RNG
    states) for the caller to fast-forward with. RNG state itself is NOT
    restored here — the resume loop restores it at the exact step
    boundary it belongs to."""
    state, meta = load_train_state(path)
    if "model" in state:
        model.network.set_state_dict(state["model"])
    if "optimizer" in state and model._optimizer is not None and \
            hasattr(model._optimizer, "set_state_dict"):
        import_optimizer_state(model, state["optimizer"])
    _fm.restores_total.labels(cause).inc()
    return meta or {}
