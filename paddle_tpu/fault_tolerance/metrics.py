"""Fault-tolerance metrics, registered at import so a scrape shows the
checkpoint/sentinel story (how often saves ran, how long the train
thread paused, how many spikes were skipped or rolled back) without
anyone taking a snapshot first.

Names follow ``paddle_tpu_checkpoint_*`` / ``paddle_tpu_loss_spike_*``;
the commit-protocol counters (``..._commits_total``,
``..._corrupt_skipped_total``) live with the protocol in
``distributed/checkpoint/atomic.py`` — same registry, one scrape.
"""

from __future__ import annotations

from ..observability import metrics as _m

__all__ = [
    "saves_total", "save_seconds", "snapshot_seconds", "save_bytes",
    "queue_blocked_seconds", "gc_deleted_total", "restores_total",
    "save_errors_total", "preemptions_total",
    "loss_spike_total", "loss_spike_skipped_updates_total",
    "loss_spike_rollbacks_total",
]

saves_total = _m.counter(
    "paddle_tpu_checkpoint_saves_total",
    "checkpoints saved, by mode", ("mode",))  # async | sync
save_seconds = _m.histogram(
    "paddle_tpu_checkpoint_save_seconds",
    "serialize+write+commit wall time (background thread for async)",
    buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
             10.0, 30.0, 60.0, 120.0))
snapshot_seconds = _m.histogram(
    "paddle_tpu_checkpoint_snapshot_seconds",
    "device->host snapshot time — the TRAIN-THREAD pause of an async save",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
             0.5, 1.0, 2.5, 5.0))
save_bytes = _m.counter(
    "paddle_tpu_checkpoint_bytes_total",
    "bytes of tensor state handed to checkpoint saves")
queue_blocked_seconds = _m.histogram(
    "paddle_tpu_checkpoint_queue_blocked_seconds",
    "train-thread wait when the bounded async queue was full",
    buckets=(0.001, 0.01, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0, 60.0))
gc_deleted_total = _m.counter(
    "paddle_tpu_checkpoint_gc_deleted_total",
    "committed checkpoint dirs deleted by retention GC")
restores_total = _m.counter(
    "paddle_tpu_checkpoint_restores_total",
    "train-state restores, by cause", ("cause",))  # resume | rollback
save_errors_total = _m.counter(
    "paddle_tpu_checkpoint_save_errors_total",
    "background checkpoint saves that raised")
preemptions_total = _m.counter(
    "paddle_tpu_preemptions_total",
    "preemption signals observed by the handler", ("signal",))

loss_spike_total = _m.counter(
    "paddle_tpu_loss_spike_total",
    "bad training steps detected by the sentinel", ("reason",))  # nan|inf|spike
loss_spike_skipped_updates_total = _m.counter(
    "paddle_tpu_loss_spike_skipped_updates_total",
    "parameter updates the sentinel skipped")
loss_spike_rollbacks_total = _m.counter(
    "paddle_tpu_loss_spike_rollbacks_total",
    "rollbacks to the last committed checkpoint after persistent spikes")
