"""FaultTolerantCheckpoint — the hapi callback tying the layer together.

Every ``save_freq_steps`` train steps it asynchronously checkpoints the
full train state (model params, optimizer accumulators + step + LR
schedule, global/epoch/step counters, numpy + jax RNG states) through
the atomic protocol; on a preemption request it runs one final
SYNCHRONOUS save at the step boundary and stops training cleanly.

``Model.fit(resume_from=...)`` consumes these checkpoints: weights and
optimizer state are restored before the loop, and the loop fast-forwards
to the saved position — re-drawing the epoch's shuffle permutation from
the saved epoch-begin RNG state, skipping the already-trained batches,
then restoring the exact step-boundary RNG states — so a killed-and-
resumed run is step-for-step bit-identical to an uninterrupted one
(asserted in tests/test_fault_tolerance.py). The LR schedule needs no
arithmetic fast-forward: its state rides in the optimizer state dict.
"""

from __future__ import annotations

import sys
from typing import Optional

import numpy as np

from ..hapi.callbacks import Callback
from .checkpointer import AsyncCheckpointer
from . import preemption as _pre

__all__ = ["FaultTolerantCheckpoint", "capture_rng_state",
           "restore_rng_state"]


def capture_rng_state() -> dict:
    """Both host RNG streams a training loop consumes: numpy's global
    generator (data shuffling) and the framework's jax key chain
    (dropout/init via ``paddle.seed``)."""
    from ..ops.random import get_rng_state

    return {"np": np.random.get_state(),
            "jax": np.asarray(get_rng_state()[0])}


def restore_rng_state(state: Optional[dict]):
    from ..ops.random import set_rng_state

    if not state:
        return
    if state.get("np") is not None:
        np.random.set_state(state["np"])
    if state.get("jax") is not None:
        set_rng_state(np.asarray(state["jax"]))


class FaultTolerantCheckpoint(Callback):
    """Periodic async train-state checkpointing + preemption save.

    Args:
        dir: checkpoint root; saves land in ``{dir}/step_{n:08d}/``.
        save_freq_steps: checkpoint every N global train steps
            (None: only the preemption/final save).
        async_save: snapshot on the train thread, commit in the
            background (False: every save is synchronous).
        max_to_keep / keep_every_n_steps: retention GC
            (``AsyncCheckpointer``).
        install_signal_handlers: route SIGTERM/SIGINT into the
            checkpoint-then-stop path.
        exit_on_preemption: after the final save and clean callback
            teardown, exit the process with code 0 (what a preemptible
            worker wants; leave False for in-process use/tests).
        save_on_train_end: also checkpoint when fit finishes normally.
    """

    def __init__(self, dir: str, save_freq_steps: Optional[int] = 100,
                 async_save: bool = True, max_to_keep: Optional[int] = None,
                 keep_every_n_steps: Optional[int] = None,
                 install_signal_handlers: bool = True,
                 exit_on_preemption: bool = False,
                 save_on_train_end: bool = True):
        super().__init__()
        self.dir = dir
        self.save_freq_steps = save_freq_steps
        self.async_save = async_save
        self.max_to_keep = max_to_keep
        self.keep_every_n_steps = keep_every_n_steps
        self.install_signal_handlers = install_signal_handlers
        self.exit_on_preemption = exit_on_preemption
        self.save_on_train_end = save_on_train_end
        self.checkpointer: Optional[AsyncCheckpointer] = None
        self.preempted = False
        self.global_step = 0
        self._epoch = 0
        self._step_in_epoch = -1
        self._rng_epoch_begin: Optional[dict] = None

    # -- lifecycle -----------------------------------------------------------
    def on_train_begin(self, logs=None):
        self.checkpointer = AsyncCheckpointer(
            self.dir, max_to_keep=self.max_to_keep,
            keep_every_n_steps=self.keep_every_n_steps)
        self.preempted = False
        resume = getattr(self.model, "_resume_state", None) or {}
        self.global_step = int(resume.get("global_step", 0))
        if self.install_signal_handlers:
            _pre.install_preemption_handler()

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._step_in_epoch = -1
        # captured BEFORE the loader draws this epoch's shuffle
        # permutation — the resume loop replays the epoch from here
        self._rng_epoch_begin = capture_rng_state()

    def on_train_batch_end(self, step, logs=None):
        self._step_in_epoch = step
        self.global_step += 1
        if _pre.preemption_requested():
            # final save MUST be synchronous: the process is about to die
            self._save(sync=True)
            self.preempted = True
            self.model.stop_training = True
            return
        if self.save_freq_steps and \
                self.global_step % self.save_freq_steps == 0:
            self._save(sync=not self.async_save)

    def on_train_end(self, logs=None):
        if self.checkpointer is None:
            return
        if self.save_on_train_end and not self.preempted \
                and self.global_step:
            self._save(sync=True)
        self.checkpointer.close()
        if self.install_signal_handlers:
            _pre.uninstall_preemption_handler()
        if self.preempted and self.exit_on_preemption:
            sys.exit(0)

    # -- the save ------------------------------------------------------------
    def _save(self, sync: bool):
        state = {"model": self.model.network.state_dict()}
        opt = self.model._optimizer
        if opt is not None and hasattr(opt, "state_dict"):
            # structured-name keys: restorable in a fresh process where
            # the p.name counter starts over (export_optimizer_state)
            from .checkpointer import export_optimizer_state

            state["optimizer"] = export_optimizer_state(self.model)
        rng = capture_rng_state()
        meta = {
            "global_step": self.global_step,
            "epoch": self._epoch,
            "step_in_epoch": self._step_in_epoch,
            "rng": rng,
            "rng_epoch_begin": self._rng_epoch_begin or rng,
        }
        self.checkpointer.save(self.global_step, state, meta=meta, sync=sync)

    def latest_checkpoint(self) -> Optional[str]:
        return self.checkpointer.latest_path() if self.checkpointer else None
