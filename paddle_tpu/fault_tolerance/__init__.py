"""paddle_tpu.fault_tolerance — survive preemptions, corrupt saves and
loss blow-ups on long training runs.

Built on the v2 atomic checkpoint protocol
(``distributed/checkpoint/atomic.py``: scratch-dir write -> fsync ->
digest ``COMMITTED`` marker -> atomic rename), this package adds the
training-loop half:

- ``AsyncCheckpointer``: millisecond device->host snapshot on the train
  thread, serialize/write/commit on a background thread with a bounded
  queue, retention GC (``max_to_keep`` / ``keep_every_n_steps``).
- ``FaultTolerantCheckpoint``: the hapi callback — periodic async
  train-state saves (params, optimizer, step, RNG), one final sync save
  on SIGTERM/SIGINT, and the checkpoints ``Model.fit(resume_from=...)``
  restores bit-identically from.
- ``LossSpikeSentinel``: robust (median/MAD) loss watch; NaN/Inf or
  >k-sigma steps get their update skipped, persistent divergence rolls
  back to the last committed checkpoint.
- preemption handler: SIGTERM/SIGINT -> "save at the next step
  boundary, then stop" (``install_preemption_handler`` /
  ``preemption_requested``).

All of it is metered (``paddle_tpu_checkpoint_*``,
``paddle_tpu_loss_spike_*``, ``paddle_tpu_preemptions_total``) through
the observability registry.
"""

from . import metrics
from .checkpointer import (AsyncCheckpointer, latest_checkpoint,
                           load_train_state, restore_train_state,
                           save_train_state, snapshot_state_dict)
from .callback import (FaultTolerantCheckpoint, capture_rng_state,
                       restore_rng_state)
from .preemption import (PreemptionHandler, clear_preemption,
                         install_preemption_handler, preemption_requested,
                         request_preemption, uninstall_preemption_handler)
from .sentinel import LossSpikeSentinel

__all__ = [
    "AsyncCheckpointer", "FaultTolerantCheckpoint", "LossSpikeSentinel",
    "PreemptionHandler", "install_preemption_handler",
    "uninstall_preemption_handler", "preemption_requested",
    "request_preemption", "clear_preemption",
    "latest_checkpoint", "save_train_state", "load_train_state",
    "restore_train_state", "snapshot_state_dict",
    "capture_rng_state", "restore_rng_state", "metrics",
]
