"""Numerical debugging toolkit.

Parity: python/paddle/amp/debugging.py — TensorCheckerConfig:173,
check_numerics:361, enable/disable_operator_stats_collection:481,
collect_operator_stats, compare_accuracy (amp/accuracy_compare.py) — plus
the FLAGS_check_nan_inf per-op checker (fluid/eager/nan_inf_utils.h:38),
which on TPU hooks the same eager dispatch every op flows through.
"""

from __future__ import annotations

import contextlib
import json
from enum import Enum
from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..core.flags import get_flags, set_flags
from ..core.tensor import Tensor
from ..ops import dispatch as _dispatch

__all__ = [
    "DebugMode", "TensorCheckerConfig", "enable_tensor_checker",
    "disable_tensor_checker", "check_numerics", "enable_operator_stats_collection",
    "disable_operator_stats_collection", "collect_operator_stats",
    "compare_accuracy",
]


class DebugMode(Enum):
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL_FOR_OVERFLOW = 2
    CHECK_ALL = 3


class TensorCheckerConfig:
    """Parity: debugging.py:173. Configures the per-op NaN/Inf checker
    (which ops, which dtypes, abort vs log)."""

    def __init__(self, enable: bool, debug_mode: DebugMode = DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir: Optional[str] = None, checked_op_list: Optional[Sequence[str]] = None,
                 skipped_op_list: Optional[Sequence[str]] = None, debug_step=None,
                 stack_height_limit: int = 1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = list(checked_op_list or [])
        self.skipped_op_list = list(skipped_op_list or [])
        self.debug_step = debug_step
        self.stack_height_limit = stack_height_limit

    def _level(self) -> int:
        # 0 = abort (raise), >=1 = log-only: matches FLAGS_check_nan_inf_level
        return 0 if self.debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT else 1


def enable_tensor_checker(checker_config: TensorCheckerConfig):
    if checker_config.enable:
        set_flags({"FLAGS_check_nan_inf": True,
                   "FLAGS_check_nan_inf_level": checker_config._level()})
    else:
        disable_tensor_checker()


def disable_tensor_checker():
    set_flags({"FLAGS_check_nan_inf": False})


def check_numerics(tensor, op_type: str = "", var_name: str = "",
                   debug_mode: DebugMode = DebugMode.CHECK_NAN_INF_AND_ABORT):
    """Count NaN/Inf in a tensor; abort or report per debug_mode (parity:
    debugging.py:361 — returns (num_nan, num_inf, num_zero) Tensors)."""
    d = tensor._data if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    num_nan = jnp.isnan(d).sum()
    num_inf = jnp.isinf(d).sum()
    num_zero = (d == 0).sum()
    if debug_mode in (DebugMode.CHECK_NAN_INF_AND_ABORT, DebugMode.CHECK_NAN_INF):
        n_nan, n_inf = int(num_nan), int(num_inf)
        if n_nan or n_inf:
            msg = (f"[check_numerics] op={op_type} var={var_name}: "
                   f"{n_nan} NaN, {n_inf} Inf detected")
            if debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
                raise FloatingPointError(msg)
            print(msg)
    return Tensor(num_nan), Tensor(num_inf), Tensor(num_zero)


def enable_operator_stats_collection():
    """Start counting (op, output dtype) pairs flowing through dispatch."""
    _dispatch._op_stats = {}


def disable_operator_stats_collection():
    """Stop collection and print the per-dtype op table (parity: the
    reference's low-precision op-list summary)."""
    stats = _dispatch._op_stats
    _dispatch._op_stats = None
    if stats is None:
        return None
    table = {}
    for (op, dt), n in sorted(stats.items()):
        table.setdefault(op, {})[dt] = n
    print("<------------------------------ op list ------------------------------>")
    header = ["op", "fp32", "fp16", "bf16", "other"]
    print("  ".join(f"{h:<28}" if h == "op" else f"{h:>8}" for h in header))
    for op, by_dt in table.items():
        fp32 = by_dt.get("float32", 0)
        fp16 = by_dt.get("float16", 0)
        bf16 = by_dt.get("bfloat16", 0)
        other = sum(v for k, v in by_dt.items() if k not in ("float32", "float16", "bfloat16"))
        print(f"{op:<28}  {fp32:>8}  {fp16:>8}  {bf16:>8}  {other:>8}")
    print("<----------------------------------------------------------------------->")
    return table


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


def _dump_stats(stats: dict, path: str):
    with open(path, "w") as f:
        json.dump({f"{op}|{dt}": n for (op, dt), n in stats.items()}, f)


def compare_accuracy(dump_path: str, another_dump_path: str, output_filename: str,
                     loss_scale: float = 1.0, dump_all_tensors: bool = False):
    """Diff two tensor-stat dumps (parity: amp/accuracy_compare.py — the
    fp32-vs-fp16 run differ). Dumps here are JSON files mapping
    'name' -> [mean, max, min] produced by dump_tensor_stats below."""
    with open(dump_path) as f:
        a = json.load(f)
    with open(another_dump_path) as f:
        b = json.load(f)
    rows = []
    for k in sorted(set(a) & set(b)):
        va, vb = np.asarray(a[k], "float64"), np.asarray(b[k], "float64")
        diff = np.abs(va - vb).max()
        rows.append({"name": k, "run1": a[k], "run2": b[k], "max_abs_diff": float(diff)})
    with open(output_filename, "w") as f:
        json.dump(rows, f, indent=1)
    return rows


def dump_tensor_stats(named_tensors, path: str):
    """Helper: dump {name: [mean, max, min]} for compare_accuracy."""
    out = {}
    for name, t in named_tensors.items():
        d = np.asarray(t._data if isinstance(t, Tensor) else t, "float64")
        out[name] = [float(d.mean()), float(d.max()), float(d.min())]
    with open(path, "w") as f:
        json.dump(out, f)
