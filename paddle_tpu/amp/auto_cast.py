"""AMP autocast.

Parity: python/paddle/amp/auto_cast.py:1029 ``auto_cast`` + amp_lists.py
(allow/block lists), fluid/eager/amp_auto_cast.h:23 (the C++ hook inside
generated forwards). TPU design: bf16 is the native half type; the
autocast hook is installed into the eager dispatch layer
(ops.dispatch.set_amp_hook) and casts op inputs per O1 lists. O2
(``decorate``) casts parameters to bf16 with fp32 master weights kept by
the optimizer (our optimizer states are fp32 already).
"""

from __future__ import annotations

import threading
from typing import Optional

import jax.numpy as jnp

from ..core import dtype as dtypes
from ..observability.metrics import _ENABLED as _obs_on
from ..observability.metrics import counter as _obs_counter
from ..ops import dispatch as _dispatch

# Ops routed through autocast while enabled, by list decision — the
# fleet counter that shows whether AMP is actually biting (a model whose
# matmuls all land in "black"/"promote" is silently running fp32).
_amp_ops = _obs_counter(
    "paddle_tpu_amp_autocast_ops_total",
    "op dispatches seen by the AMP autocast hook while enabled, by "
    "list decision", ("list",))

# O1 lists (subset of reference amp_lists.py FP16_WHITE_LIST / BLACK_LIST).
white_list = {
    "matmul", "mm", "bmm", "linear", "conv1d", "conv2d", "conv3d", "conv2d_transpose",
    "einsum", "sdpa", "flash_attention", "addmm",
}
black_list = {
    "exp", "log", "log2", "log10", "log1p", "logsumexp", "mean", "sum", "softmax",
    "log_softmax", "cross_entropy", "bce_with_logits", "binary_cross_entropy",
    "layer_norm", "rms_norm", "batch_norm", "group_norm", "instance_norm",
    "cos_sim", "softmax_with_cross_entropy", "pow", "square", "reciprocal", "rsqrt",
    "norm", "nll_loss", "kl_div", "mse_loss", "l1_loss", "smooth_l1_loss",
}

_state = threading.local()


def _st():
    if not hasattr(_state, "enabled"):
        _state.enabled = False
        _state.dtype = jnp.bfloat16
        _state.level = "O1"
        _state.custom_white = set()
        _state.custom_black = set()
    return _state


def _amp_hook(op_name: str, datas):
    st = _st()
    if not st.enabled:
        return datas
    wl = (white_list | st.custom_white) - st.custom_black
    bl = (black_list | st.custom_black) - st.custom_white
    if op_name in wl:
        if _obs_on[0]:
            _amp_ops.labels("white").inc()
        return [d.astype(st.dtype) if d.dtype in (jnp.float32, jnp.float16, jnp.bfloat16) and d.dtype != st.dtype else d
                for d in datas]
    if op_name in bl:
        if _obs_on[0]:
            _amp_ops.labels("black").inc()
        return [d.astype(jnp.float32) if d.dtype in (jnp.float16, jnp.bfloat16) else d for d in datas]
    # gray zone: promote to widest float among inputs
    fdts = [d.dtype for d in datas if d.dtype in (jnp.float16, jnp.bfloat16, jnp.float32)]
    if fdts and any(dt == jnp.float32 for dt in fdts) and any(dt != jnp.float32 for dt in fdts):
        if _obs_on[0]:
            _amp_ops.labels("promote").inc()
        return [d.astype(jnp.float32) if d.dtype in (jnp.float16, jnp.bfloat16) else d for d in datas]
    return datas


_dispatch.set_amp_hook(_amp_hook)


class auto_cast:
    """Context manager: O1 autocasting (and O2: everything-not-black in low
    precision)."""

    def __init__(self, enable=True, custom_white_list=None, custom_black_list=None,
                 level="O1", dtype="bfloat16", use_promote=True):
        self._enable = enable
        self._level = level
        self._dtype = dtypes.convert_dtype(dtype)
        self._white = set(custom_white_list or ())
        self._black = set(custom_black_list or ())

    def __enter__(self):
        st = _st()
        self._saved = (st.enabled, st.dtype, st.level, st.custom_white, st.custom_black)
        st.enabled = self._enable
        st.dtype = self._dtype
        st.level = self._level
        st.custom_white = self._white
        st.custom_black = self._black
        return self

    def __exit__(self, *exc):
        st = _st()
        st.enabled, st.dtype, st.level, st.custom_white, st.custom_black = self._saved
        return False


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16", master_weight=None,
             save_dtype=None, master_grad=False, excluded_layers=None):
    """O2: cast model params to low precision (master weights live in the
    optimizer's fp32 state — ``master_weight`` asks for exactly what the
    fp32 accumulators already provide, so None/True are both satisfied).
    O1 keeps params fp32 (autocast handles per-op precision) — decorate
    is then an identity on the model. Parity: amp/auto_cast.py:1114."""
    d = dtypes.convert_dtype(dtype)
    from ..nn.layer import Layer

    if level == "O1":
        # O1 never casts parameters; auto_cast() does per-op casting
        if optimizers is None:
            return models
        return models, optimizers
    if level != "O2":
        raise ValueError(f"decorate level must be 'O1' or 'O2', got {level!r}")
    if master_weight is False:
        raise NotImplementedError(
            "master_weight=False (low-precision optimizer state) is not "
            "implemented: optimizers keep fp32 accumulators by design")
    if master_grad:
        raise NotImplementedError(
            "master_grad=True (fp32 gradient copies) is not implemented; "
            "grads follow param dtype and the update math is fp32")
    if save_dtype is not None:
        raise NotImplementedError(
            "save_dtype is not implemented; cast state_dicts explicitly "
            "before saving")

    def _cast_layer(layer):
        from ..nn.layers_conv_norm import _BatchNormBase, GroupNorm, LayerNorm

        for sub in layer.sublayers(include_self=True):
            if isinstance(sub, (_BatchNormBase, LayerNorm, GroupNorm)):
                continue
            if excluded_layers and isinstance(sub, tuple(excluded_layers)):
                continue
            for pname, p in sub._parameters.items():
                if p is not None and dtypes.is_floating_point(p._data.dtype):
                    p._data = p._data.astype(d)
        layer._casted_by_pure_fp16 = True
        return layer

    single_model = isinstance(models, Layer)
    model_list = [models] if single_model else list(models)
    model_list = [_cast_layer(m) for m in model_list]
    models_out = model_list[0] if single_model else model_list
    if optimizers is None:
        return models_out
    return models_out, optimizers
