"""GradScaler: dynamic loss scaling.

Parity: python/paddle/amp/grad_scaler.py:657 GradScaler (scale, step,
update, minimize; dynamic loss scaling with incr/decr ratios). On TPU
training is bf16-native so scaling is usually unnecessary (enable=False
is a no-op passthrough, like the reference when fp16 is off), but the
full fp16 semantics are implemented for parity.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.autograd import no_grad
from ..core.tensor import Tensor


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=65536.0, incr_ratio=2.0, decr_ratio=0.5,
                 incr_every_n_steps=2000, decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def scale(self, var: Tensor) -> Tensor:
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list:
            if p._grad_data is not None:
                g = p._grad_data.astype(jnp.float32) * inv
                if not bool(jnp.isfinite(g).all()):
                    found = True
                p._grad_data = g.astype(p._grad_data.dtype)
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def update(self):
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n_nan_or_inf:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)

    def is_enable(self) -> bool:
        return self._enable

    def is_use_dynamic_loss_scaling(self) -> bool:
        return self._dynamic

    def get_loss_scaling(self) -> float:
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every_n_steps,
            "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
            "incr_count": self._good_steps,
            "decr_count": self._bad_steps,
            "use_dynamic_loss_scaling": self._dynamic,
        }

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("incr_count", 0)
        self._bad_steps = state.get("decr_count", 0)


AmpScaler = GradScaler
