"""paddle.autograd namespace: backward, grad, PyLayer, hooks.

Parity: python/paddle/autograd/ (py_layer.py:36 PyLayer, backward_mode.py
backward, saved_tensors_hooks).
"""

from __future__ import annotations

from typing import Any, List

import jax.numpy as jnp

from ..core.autograd import (
    Edge,
    GradNode,
    backward,
    enable_grad,
    grad,
    is_grad_enabled,
    no_grad,
    set_grad_enabled,
)
from ..core.tensor import Tensor


class PyLayerContext:
    """Parity: python/paddle/autograd/py_layer.py PyLayerContext —
    save_for_backward / saved_tensor + arbitrary attribute stashing."""

    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    def saved_tensor(self):
        return self._saved


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """User-defined autograd function (parity: py_layer.py:268 PyLayer).

    Subclass with @staticmethod forward(ctx, *args) and backward(ctx,
    *grads); call via .apply(). The backward callable is registered as a
    GradNode on the tape, so hooks/accumulation behave identically to
    built-in ops.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        outs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outs, (tuple, list))
        outs_list = [outs] if single else list(outs)

        record = is_grad_enabled() and any(not t.stop_gradient for t in tensor_inputs)
        if record:
            out_specs = [(tuple(o._data.shape), o._data.dtype) for o in outs_list]

            def vjp_fn(cots):
                cot_list = [cots] if len(outs_list) == 1 else list(cots)
                cot_tensors = [Tensor(c, stop_gradient=True) for c in cot_list]
                with no_grad():
                    grads = cls.backward(ctx, *cot_tensors)
                if not isinstance(grads, (tuple, list)):
                    grads = (grads,)
                out = []
                for g in grads:
                    out.append(None if g is None else (g._data if isinstance(g, Tensor) else jnp.asarray(g)))
                return tuple(out)

            edges = []
            for t in tensor_inputs:
                if t.stop_gradient:
                    edges.append(Edge())
                elif t._grad_node is not None:
                    edges.append(Edge(node=t._grad_node, slot=t._out_slot))
                else:
                    edges.append(Edge(leaf=t))
            node = GradNode(cls.__name__, vjp_fn, edges, out_specs)

            def taped_vjp(cot_tensors):
                # create_graph path (parity: py_layer.py:268): run the
                # USER'S backward with the tape ON — its ops are recorded,
                # so paddle.grad(..., create_graph=True) differentiates the
                # custom backward itself (saved tensors keep their forward
                # tape links, carrying d²/dx² through ctx.saved_tensor())
                return cls.backward(ctx, *cot_tensors)  # caller normalizes

            node.taped_vjp = taped_vjp
            for i, o in enumerate(outs_list):
                from ..core import dtype as dtypes

                if dtypes.is_floating_point(o._data.dtype):
                    o.stop_gradient = False
                    o._grad_node = node
                    o._out_slot = i
        return outs_list[0] if single else tuple(outs_list)


class saved_tensors_hooks:
    """Parity: python/paddle/autograd/saved_tensors_hooks.py. The eager tape
    stores residuals inside XLA pullbacks, so pack/unpack hooks apply only
    to PyLayer-saved tensors; kept for API compatibility."""

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
