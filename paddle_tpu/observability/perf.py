"""Performance observability: per-executable cost/roofline attribution,
the HBM ledger, OOM forensics, and the perf-regression gate helpers.

The fourth leg of the observability stack. The metrics half (PR 2)
counts events, the tracing half (PR 7) timelines requests; this module
answers the *efficiency* questions — is this executable compute- or
bandwidth-bound, what is its MFU, and where did the HBM go — the
numbers MFU-accounting practice (PaLM-style ``model_flops /
peak_flops`` reporting) and vLLM-class serving systems treat as
first-class telemetry.

How capture works (zero extra compiles, host-side only):

- every XLA compilation funnels through
  ``jax._src.compiler.backend_compile`` — the same choke point that
  emits the ``backend_compile_duration`` monitoring event the
  recompile monitor listens to. ``install()`` wraps it once; the
  wrapper reads the recompile monitor's ``entrypoint()`` stack (the
  compile runs synchronously on the dispatching thread) and extracts
  ``cost_analysis()`` / ``get_compiled_memory_stats()`` from the
  freshly built executable. Nothing is recompiled, nothing touches the
  dispatch fast path — capture costs one dict-read per *compile*.
- an entry that compiles several programs (e.g. a tiny dtype-convert
  plus the real step) keeps the DOMINANT executable's analysis (max
  flops, then max bytes) and counts the rest.
- per-entry wall timings ride the existing ``entrypoint()`` scopes via
  ``recompile.add_call_hook`` (two clock reads per entry call — the
  engine's step loop already pays more than that for its histogram),
  so the ledger can join static FLOPs/bytes with measured time into
  achieved FLOP/s, achieved GB/s, and MFU. Caveat: a persistent-
  compilation-cache hit skips ``backend_compile`` — use
  ``capture_compiled(entry, compiled)`` to seed the ledger explicitly
  on such lanes (the AOT helpers below return the analyses either
  way).

Roofline classification compares each entry's arithmetic intensity
(flops / bytes accessed) against the device's machine balance
(peak FLOP/s / peak bytes/s) from ``peak_specs()``: a published
per-chip peak table with ``PADDLE_TPU_PEAK_FLOPS`` /
``PADDLE_TPU_PEAK_HBM_GBPS`` env overrides. CPU (and unknown device
kinds) get honest ``None`` peaks and a ``"unknown"`` roofline class —
never a made-up MFU. Note the GSPMD convention: ``cost_analysis`` for
a partitioned program reports PER-PARTITION numbers, matching the
per-chip peaks and the per-chip MFU convention.

The **HBM ledger** (``hbm_ledger()``) attributes live device bytes to
subsystems: components registered by their owners (the serving engine
registers its KV pools and model weights; ``ShardedTrainStep``
registers params/optimizer state), per-executable temp/output sizes
from the captured memory analyses, and headroom against PJRT's
``bytes_limit`` (``core/memory.py`` accessors; ``"unsupported"``
where the transport reports nothing — the one shared fallback label,
``MEMORY_STATS_UNSUPPORTED``).

**OOM forensics**: ``is_oom_error`` recognizes RESOURCE_EXHAUSTED /
allocator-failure shapes, and ``dump_oom`` writes a flight-recorder
dump whose ``extra`` names the top-k executables by temp bytes next to
the HBM ledger — so an OOM names its culprit instead of dying with an
XLA backtrace. A ``perf`` state provider is registered with the
flight recorder, so EVERY dump (engine crash, pool exhaustion,
SIGTERM) carries the ledger too.

**Perf-regression gate**: ``collect_bench_metrics`` flattens the
committed bench artifacts (serving / paged-KV / spec-decode tok/s,
capacity ratios), ``load_baseline`` reads
``benchmarks/perf_baseline.json`` (per-metric value + pinned
tolerance), and ``compare_to_baseline`` produces the verdict
``run_shards.py`` merges into ``telemetry_lane.json`` and fails the
lane on. This is what starts populating the BENCH_* trajectory
artifacts going forward.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from . import metrics as _m
from . import recompile as _rc

__all__ = [
    "install", "installed", "enable", "disable", "perf_enabled",
    "extract_cost_analysis", "extract_memory_analysis",
    "capture_compiled", "MEMORY_STATS_UNSUPPORTED",
    "peak_specs", "PEAK_FLOPS_ENV", "PEAK_HBM_ENV",
    "ledger", "ledger_entry", "note_entry_items", "reset",
    "register_memory_component", "unregister_memory_component",
    "hbm_ledger",
    "is_oom_error", "oom_report", "dump_oom",
    "collect_bench_metrics", "load_baseline", "compare_to_baseline",
    "mfu_gauge", "hbm_bw_util_gauge",
]

logger = logging.getLogger("paddle_tpu.observability")

# The one PJRT-absent fallback label: StepTelemetry JSONL records, the
# profiler summary, and the HBM ledger all spell "memory_stats gave us
# nothing" the same way.
MEMORY_STATS_UNSUPPORTED = "unsupported"
# ...and the human-facing spelling the profiler summary table prints.
PJRT_MEMORY_UNSUPPORTED_NOTE = (
    f"n/a (PJRT memory_stats {MEMORY_STATS_UNSUPPORTED})")

PEAK_FLOPS_ENV = "PADDLE_TPU_PEAK_FLOPS"
PEAK_HBM_ENV = "PADDLE_TPU_PEAK_HBM_GBPS"

# Published per-CHIP peaks: (dense bf16 FLOP/s, HBM GB/s). Matched
# against jax's device_kind by longest prefix, so "TPU v4 (podslice)"
# style strings still resolve. CPU is deliberately absent: no honest
# peak exists for arbitrary hosts, and the env override is the escape
# hatch for anything unlisted.
_PEAK_TABLE = (
    ("TPU v6", (918e12, 1640.0)),   # Trillium
    ("TPU v5p", (459e12, 2765.0)),
    ("TPU v5 lite", (197e12, 819.0)),
    ("TPU v5e", (197e12, 819.0)),
    ("TPU v4", (275e12, 1228.0)),
    ("TPU v3", (123e12, 900.0)),
    ("TPU v2", (45e12, 600.0)),
)

_enabled = [os.environ.get("PADDLE_TPU_PERF", "1") != "0"]
_installed = [False]
_install_lock = threading.Lock()

_lock = threading.Lock()
# entry -> ledger record (see _new_rec); writer paths take _lock only
# on compile capture (rare); the per-call hook appends to a deque.
_entries: Dict[str, dict] = {}

# timing window per entry: achieved numbers use the recent mean so a
# slow warmup call ages out of the published MFU
_TIMING_WINDOW = 64

# thread-local set of entries that compiled during the CURRENT call:
# the call hook drops that call's wall time (it includes the XLA
# compile — folding it in would understate steady-state MFU wildly)
_tls = threading.local()

mfu_gauge = _m.gauge(
    "paddle_tpu_mfu",
    "model FLOPs utilization per jitted entry point: captured "
    "executable flops / recent mean call time / peak device FLOP/s "
    "(absent peaks -> gauge not set)", ("entry",))
hbm_bw_util_gauge = _m.gauge(
    "paddle_tpu_hbm_bw_util",
    "achieved HBM bandwidth fraction per jitted entry point: captured "
    "bytes accessed / recent mean call time / peak HBM bytes/s "
    "(absent peaks -> gauge not set)", ("entry",))
_captures_total = _m.counter(
    "paddle_tpu_perf_captures_total",
    "compiled executables whose cost/memory analysis was captured into "
    "the perf ledger", ("entry",))
_oom_dumps_total = _m.counter(
    "paddle_tpu_oom_dumps_total",
    "OOM forensics dumps written (flight-recorder dumps triggered by "
    "allocation failures)")


def enable():
    _enabled[0] = True


def disable():
    """Reduce the capture + timing sites to one flag check (the bench
    A/B lane's OFF arm)."""
    _enabled[0] = False


def perf_enabled() -> bool:
    return _enabled[0] and _m._ENABLED[0]


# ---------------------------------------------------------------------------
# analysis extraction (the ONE cost-extraction path; distributed/engine.py
# and the profiler route through these)
# ---------------------------------------------------------------------------


def extract_cost_analysis(compiled) -> Optional[dict]:
    """XLA's per-execution cost model as ``{"flops", "bytes_accessed"}``
    from either a ``jax.stages.Compiled`` or a raw PJRT
    ``LoadedExecutable``; ``None`` when the backend reports nothing.
    GSPMD-partitioned programs report PER-PARTITION numbers (one
    device's share — the per-chip MFU convention)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):  # older jax / raw PJRT wrap in a list
        ca = ca[0] if ca else None
    if not ca:
        return None
    flops = ca.get("flops")
    bytes_accessed = ca.get("bytes accessed")
    if flops is None and bytes_accessed is None:
        return None
    return {"flops": flops, "bytes_accessed": bytes_accessed}


_MEM_FIELDS = (
    ("argument_bytes", "argument_size_in_bytes"),
    ("output_bytes", "output_size_in_bytes"),
    ("temp_bytes", "temp_size_in_bytes"),
    ("generated_code_bytes", "generated_code_size_in_bytes"),
)


def extract_memory_analysis(compiled) -> Optional[dict]:
    """The compiled program's HBM breakdown (argument/output/temp/
    generated-code bytes) from either a ``jax.stages.Compiled``
    (``memory_analysis()``) or a raw PJRT ``LoadedExecutable``
    (``get_compiled_memory_stats()``); ``None`` when unsupported."""
    ma = None
    for getter in ("memory_analysis", "get_compiled_memory_stats"):
        fn = getattr(compiled, getter, None)
        if fn is None:
            continue
        try:
            ma = fn()
        except Exception:
            ma = None
        if ma is not None:
            break
    if ma is None:
        return None
    out = {k: getattr(ma, attr, None) for k, attr in _MEM_FIELDS}
    if all(v is None for v in out.values()):
        return None
    return out


# ---------------------------------------------------------------------------
# capture (rides the backend_compile funnel + the entrypoint() stack)
# ---------------------------------------------------------------------------


def _new_rec() -> dict:
    return {
        "flops": None, "bytes_accessed": None,
        "argument_bytes": None, "output_bytes": None,
        "temp_bytes": None, "generated_code_bytes": None,
        "compiles_captured": 0, "captured_ts": None,
        "calls": 0, "total_time_s": 0.0, "items": 0,
        "mesh": None,
        "recent": deque(maxlen=_TIMING_WINDOW),
    }


def _rec(entry: str) -> dict:
    rec = _entries.get(entry)
    if rec is None:
        with _lock:
            rec = _entries.setdefault(entry, _new_rec())
    return rec


def capture_compiled(entry: str, compiled) -> Optional[dict]:
    """Record ``compiled``'s cost/memory analysis under ``entry`` —
    keeping the dominant executable when the entry already holds one.
    The backend_compile wrapper calls this for every compile; callers
    on persistent-cache-hit lanes (where backend_compile is skipped)
    can seed the ledger explicitly. Returns the stored analysis."""
    cost = extract_cost_analysis(compiled)
    mem = extract_memory_analysis(compiled)
    if cost is None and mem is None:
        return None
    rec = _rec(entry)
    with _lock:
        rec["compiles_captured"] += 1
        new_key = ((cost or {}).get("flops") or 0.0,
                   (cost or {}).get("bytes_accessed") or 0.0)
        old_key = (rec["flops"] or 0.0, rec["bytes_accessed"] or 0.0)
        if rec["captured_ts"] is None or new_key >= old_key:
            if cost:
                rec["flops"] = cost["flops"]
                rec["bytes_accessed"] = cost["bytes_accessed"]
            if mem:
                for k, _ in _MEM_FIELDS:
                    rec[k] = mem[k]
            rec["captured_ts"] = time.time()
    compiled_now = getattr(_tls, "compiled", None)
    if compiled_now is None:
        compiled_now = _tls.compiled = set()
    compiled_now.add(entry)
    _captures_total.labels(entry).inc()
    return {**(cost or {}), **(mem or {})}


def _on_entry_call(entry: str, dt_s: float):
    """recompile.entrypoint exit hook: the measured-wall-time half of
    the ledger join (StepTelemetry/step histograms already time the
    same scopes; this keeps the per-ENTRY association). A call whose
    scope compiled something is warmup — its wall time (which includes
    the XLA compile) is excluded from the achieved-rate window."""
    if not perf_enabled():
        return
    compiled_now = getattr(_tls, "compiled", None)
    if compiled_now and entry in compiled_now:
        compiled_now.discard(entry)
        return
    rec = _rec(entry)
    rec["calls"] += 1
    rec["total_time_s"] += dt_s
    rec["recent"].append(dt_s)


def note_entry_mesh(entry: str, axes: Dict[str, int]):
    """Tag ``entry`` as compiled over a device mesh (e.g. ``{"tp": 2}``).

    XLA's cost/memory analysis is captured from the PARTITIONED module,
    so a tagged entry's flops/bytes — and the MFU/roofline derived from
    them against the single-chip peaks — are PER-DEVICE numbers; the
    tag records the mesh so ledger readers can aggregate (multiply by
    the axis product) instead of misreading a tp=4 step as one chip's
    work. Owners call this once at executable build (the serving engine
    does for every ``serving.*`` entry when ``tp > 1``)."""
    _rec(entry)["mesh"] = {k: int(v) for k, v in axes.items()}


def note_entry_items(entry: str, n: int):
    """Credit ``n`` processed items (tokens, samples) to ``entry`` so
    the ledger can report bytes/token and tokens/s. Host-side integer
    add — call it from the code that already knows the count (the
    serving step loop, generate)."""
    if not perf_enabled():
        return
    _rec(entry)["items"] += int(n)


def install() -> bool:
    """Wrap ``jax._src.compiler.backend_compile`` (idempotent) so every
    XLA compile contributes its analyses to the ledger, attributed via
    the recompile monitor's entrypoint stack. Also registers the
    entry-call timing hook and the flight-recorder state provider."""
    if _installed[0]:
        return True
    with _install_lock:
        if _installed[0]:
            return True
        try:
            from jax._src import compiler as _jcompiler
        except Exception:
            return False
        orig = _jcompiler.backend_compile

        def _backend_compile_captured(backend, module, options,
                                      host_callbacks):
            exe = orig(backend, module, options, host_callbacks)
            if perf_enabled():
                try:
                    capture_compiled(_rc.current_entry(), exe)
                except Exception:  # capture must never break a compile
                    logger.debug("perf capture failed", exc_info=True)
            return exe

        _jcompiler.backend_compile = _backend_compile_captured
        _rc.add_call_hook(_on_entry_call)
        from . import tracing as _tracing

        _tracing.register_state_provider("perf", _state_provider)
        _installed[0] = True
        return True


def installed() -> bool:
    return _installed[0]


def reset():
    """Clear the ledger + memory components (tests)."""
    with _lock:
        _entries.clear()
    with _components_lock:
        _components.clear()


# ---------------------------------------------------------------------------
# peaks + roofline
# ---------------------------------------------------------------------------


def _device_kind() -> Optional[str]:
    try:
        import jax

        return jax.devices()[0].device_kind
    except Exception:
        return None


def peak_specs(device_kind: Optional[str] = None) -> dict:
    """Peak FLOP/s and HBM GB/s for the attached device: env overrides
    (``PADDLE_TPU_PEAK_FLOPS`` in FLOP/s, ``PADDLE_TPU_PEAK_HBM_GBPS``
    in GB/s) beat the published per-chip table; unknown kinds — CPU
    included — get honest ``None`` peaks, never a guess."""
    kind = device_kind if device_kind is not None else _device_kind()
    flops = hbm = None
    source = "unknown"
    if kind:
        for prefix, (f, b) in _PEAK_TABLE:
            if kind.startswith(prefix):
                flops, hbm, source = f, b, "table"
                break
    env_f = os.environ.get(PEAK_FLOPS_ENV)
    env_b = os.environ.get(PEAK_HBM_ENV)
    try:
        if env_f:
            flops, source = float(env_f), "env"
        if env_b:
            hbm = float(env_b)
            source = "env"
    except ValueError:
        logger.warning("bad %s/%s value (want a number): %r / %r",
                       PEAK_FLOPS_ENV, PEAK_HBM_ENV, env_f, env_b)
    return {
        "device_kind": kind,
        "peak_flops_per_s": flops,
        "peak_hbm_gbps": hbm,
        "machine_balance_flops_per_byte": (
            flops / (hbm * 1e9) if flops and hbm else None),
        "source": source,
    }


def roofline_class(intensity: Optional[float],
                   peaks: Optional[dict] = None) -> str:
    """``"compute-bound"`` / ``"bandwidth-bound"`` against the machine
    balance, ``"unknown"`` when either the intensity or the peaks are
    absent (CPU's honest answer)."""
    if peaks is None:
        peaks = peak_specs()
    balance = peaks.get("machine_balance_flops_per_byte")
    if intensity is None or balance is None:
        return "unknown"
    return "compute-bound" if intensity >= balance else "bandwidth-bound"


# ---------------------------------------------------------------------------
# the ledger
# ---------------------------------------------------------------------------


def ledger_entry(entry: str, peaks: Optional[dict] = None,
                 publish: bool = False) -> Optional[dict]:
    """One entry's JSON-ready ledger row, joining the captured static
    analysis with the measured entry timings."""
    rec = _entries.get(entry)
    if rec is None:
        return None
    if peaks is None:
        peaks = peak_specs()
    with _lock:
        recent = list(rec["recent"])
        row = {k: rec[k] for k in (
            "flops", "bytes_accessed", "argument_bytes", "output_bytes",
            "temp_bytes", "generated_code_bytes", "compiles_captured",
            "calls", "total_time_s", "items")}
    mean_t = (sum(recent) / len(recent)) if recent else None
    flops, nbytes = row["flops"], row["bytes_accessed"]
    # mesh-tagged entries (note_entry_mesh): the captured analysis is
    # the partitioned module's, so flops/bytes/MFU below are PER-DEVICE;
    # mesh_flops/mesh_bytes_accessed give the whole-mesh totals
    mesh = rec.get("mesh")
    row["mesh"] = dict(mesh) if mesh else None
    if mesh:
        ndev = 1
        for v in mesh.values():
            ndev *= int(v)
        row["mesh_devices"] = ndev
        row["mesh_flops"] = flops * ndev if flops else None
        row["mesh_bytes_accessed"] = nbytes * ndev if nbytes else None
    row["mean_time_s"] = mean_t
    row["arithmetic_intensity"] = (
        flops / nbytes if flops and nbytes else None)
    row["achieved_flops_per_s"] = (
        flops / mean_t if flops and mean_t else None)
    row["achieved_gbps"] = (
        nbytes / mean_t / 1e9 if nbytes and mean_t else None)
    pf = peaks.get("peak_flops_per_s")
    pb = peaks.get("peak_hbm_gbps")
    row["mfu"] = (row["achieved_flops_per_s"] / pf
                  if row["achieved_flops_per_s"] and pf else None)
    row["hbm_bw_util"] = (row["achieved_gbps"] / pb
                          if row["achieved_gbps"] and pb else None)
    row["roofline"] = roofline_class(row["arithmetic_intensity"], peaks)
    row["bytes_per_item"] = (
        nbytes * row["calls"] / row["items"]
        if nbytes and row["items"] else None)
    row["items_per_s"] = (
        row["items"] / row["total_time_s"]
        if row["items"] and row["total_time_s"] else None)
    if publish:
        if row["mfu"] is not None:
            mfu_gauge.labels(entry).set(row["mfu"])
        if row["hbm_bw_util"] is not None:
            hbm_bw_util_gauge.labels(entry).set(row["hbm_bw_util"])
    return row


def ledger(prefix: Optional[str] = None) -> Dict[str, dict]:
    """Every captured entry's ledger row (optionally filtered to one
    name prefix, e.g. ``"serving."``). Reading the ledger publishes the
    ``paddle_tpu_mfu`` / ``paddle_tpu_hbm_bw_util`` gauges — scrape
    freshness follows snapshot/stats reads, not the decode hot path."""
    peaks = peak_specs()
    out = {}
    for entry in sorted(_entries):
        if prefix is not None and not entry.startswith(prefix):
            continue
        row = ledger_entry(entry, peaks, publish=True)
        if row is not None:
            out[entry] = row
    return out


# ---------------------------------------------------------------------------
# HBM ledger (live device bytes -> subsystems)
# ---------------------------------------------------------------------------

_components: Dict[str, Callable[[], Optional[dict]]] = {}
_components_lock = threading.Lock()


def register_memory_component(name: str, fn: Callable[[], Optional[dict]]):
    """Register a zero-arg callable returning ``{"bytes": int, ...}``
    (or ``None`` to drop out — weakref-closure friendly, the engine
    pattern) attributed as one subsystem row of the HBM ledger."""
    with _components_lock:
        _components[name] = fn


def unregister_memory_component(name: str):
    with _components_lock:
        _components.pop(name, None)


def hbm_ledger(top_k: int = 8) -> dict:
    """Attribute live device bytes to subsystems:

    - ``device``: PJRT live/peak/limit + headroom (``"unsupported"``
      where ``memory_stats()`` reports nothing — CPU commonly),
    - ``components``: every registered subsystem's own accounting (KV
      pools per format, model weights, optimizer state, ...),
    - ``executables``: top-k captured entries by temp bytes (the
      compiler-owned scratch an OOM usually hides in) with output and
      argument sizes alongside.
    """
    from ..core import memory as _cm

    stats = _cm.device_memory_stats()
    live = stats.get("bytes_in_use")
    peak = stats.get("peak_bytes_in_use")
    limit = stats.get("bytes_limit")
    headroom = _cm.memory_headroom()
    device = {
        "live_bytes": live if live is not None else MEMORY_STATS_UNSUPPORTED,
        "peak_bytes": peak if peak is not None else MEMORY_STATS_UNSUPPORTED,
        "bytes_limit": (limit if limit is not None
                        else MEMORY_STATS_UNSUPPORTED),
        "headroom_bytes": (headroom if headroom is not None
                           else MEMORY_STATS_UNSUPPORTED),
    }
    with _components_lock:
        items = list(_components.items())
    components = {}
    for name, fn in items:
        try:
            c = fn()
        except Exception as e:  # noqa: BLE001 — the ledger must survive
            c = {"error": repr(e)}
        if c is not None:
            components[name] = c
    rows = []
    with _lock:
        for entry, rec in _entries.items():
            if rec["temp_bytes"] is None and rec["output_bytes"] is None:
                continue
            rows.append({
                "entry": entry,
                "temp_bytes": rec["temp_bytes"],
                "output_bytes": rec["output_bytes"],
                "argument_bytes": rec["argument_bytes"],
                "generated_code_bytes": rec["generated_code_bytes"],
            })
    rows.sort(key=lambda r: (r["temp_bytes"] or 0, r["output_bytes"] or 0),
              reverse=True)
    attributed = sum((c.get("bytes") or 0) for c in components.values()
                     if isinstance(c, dict))
    return {
        "device": device,
        "components": components,
        "component_bytes_total": attributed,
        "unattributed_bytes": (live - attributed if live is not None
                               else MEMORY_STATS_UNSUPPORTED),
        "executables": rows[:top_k],
    }


def _state_provider() -> dict:
    """The flight-recorder ``perf`` section: every dump — engine crash,
    pool exhaustion, SIGTERM — carries the ledger + HBM attribution."""
    return {"ledger": ledger(), "hbm": hbm_ledger(),
            "peaks": peak_specs()}


def perf_snapshot() -> dict:
    """The ``observability.snapshot()["perf"]`` section."""
    return {"enabled": perf_enabled(), "ledger": ledger(),
            "hbm": hbm_ledger(), "peaks": peak_specs()}


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------

_OOM_MARKERS = (
    "RESOURCE_EXHAUSTED", "RESOURCE EXHAUSTED", "Out of memory",
    "out of memory", "OOM", "Allocation failure",
    "failed to allocate", "Failed to allocate", "PoolExhausted",
)


def is_oom_error(exc: BaseException) -> bool:
    """Does this exception look like a device allocation failure
    (XLA RESOURCE_EXHAUSTED, PJRT allocator failure, or the engine's
    own PoolExhaustedError family)?"""
    text = f"{type(exc).__name__}: {exc}"
    return any(m in text for m in _OOM_MARKERS)


def oom_report(top_k: int = 5) -> dict:
    """The forensics payload: HBM ledger + the top-k executables by
    temp bytes (named, so the dump points at the culprit program)."""
    hbm = hbm_ledger(top_k=top_k)
    top = hbm["executables"]
    return {
        "hbm": hbm,
        "peaks": peak_specs(),
        "top_temp_executables": top,
        "suspect": top[0]["entry"] if top else None,
    }


def dump_oom(exc: BaseException, reason: str = "oom",
             top_k: int = 5) -> Optional[str]:
    """Write the OOM forensics flight-recorder dump: the ledger, the
    top-k temp-byte executables, and the active trace (the dump's
    event ring). Returns the dump path (None if the write failed —
    never masks the original error)."""
    from . import tracing as _tracing

    try:
        extra = {"error": repr(exc), **oom_report(top_k=top_k)}
    except Exception:  # noqa: BLE001 — forensics must not crash twice
        extra = {"error": repr(exc)}
    path = _tracing.flight_dump(reason, extra=extra)
    if path is not None:
        _oom_dumps_total.inc()
    return path


# ---------------------------------------------------------------------------
# perf-regression gate (benchmarks/perf_baseline.json)
# ---------------------------------------------------------------------------


def _dig(d: Any, path: str) -> Optional[float]:
    cur = d
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur if isinstance(cur, (int, float)) else None


# metric name -> (artifact file, dotted path). One place defines what
# the gate watches; collect_bench_metrics + the committed baseline
# stay in sync through it.
BENCH_METRIC_SOURCES = {
    "serving.tok_s": ("bench_serving.json", "serving.tok_s"),
    "serving.speedup_vs_sequential": ("bench_serving.json", "speedup"),
    "paged.tok_s": ("bench_paged_kv.json", "capacity_ab.paged.tok_s"),
    "paged.capacity_ratio": ("bench_paged_kv.json",
                             "capacity_ab.capacity_ratio"),
    "paged.int8_capacity_vs_bf16": (
        "bench_paged_kv.json", "kv_format_ab.formats.int8.capacity_vs_bf16"),
    "spec.best_speedup": ("bench_spec_decode.json", "best_speedup"),
    "spec.k8_occ1_tok_s": ("bench_spec_decode.json",
                           "spec_k8_coupled.by_occupancy.1.tok_s"),
    "spec_tree.tok_s_ratio_vs_chain": ("bench_spec_decode.json",
                                       "spec_tree.tok_s_ratio_vs_chain"),
    "spec_tree.parity": ("bench_spec_decode.json", "spec_tree.parity"),
    "router.tok_s": ("bench_router.json", "goodput.tok_s"),
    "router.overhead_pct": ("bench_router.json", "overhead.overhead_pct"),
    "router.fleet_overhead_pct": ("bench_router.json",
                                  "fleet_overhead.overhead_pct"),
    "router.crash_completed_frac": ("bench_router.json",
                                    "crash.completed_frac"),
    "kv_tier.saved_frac_longconv": ("bench_kv_tier.json",
                                    "long_conversation.saved_frac"),
    "kv_tier.readmit_speedup": ("bench_kv_tier.json",
                                "long_conversation.readmit_speedup"),
    "kv_tier.parity": ("bench_kv_tier.json", "parity_all"),
    "tp.tp2_tok_s": ("bench_tp.json", "lanes.tp2.tok_s"),
    "tp.parity": ("bench_tp.json", "parity_all"),
    "tp.weight_hbm_frac_tp2": ("bench_tp.json",
                               "lanes.tp2.weight_bytes_per_device_frac"),
    "train.tok_s_per_chip": ("bench_train.json", "tokens_per_sec_per_chip"),
    "train.mfu": ("bench_train.json", "mfu"),
    "overload.supervisor_overhead_pct": ("bench_overload.json",
                                         "overhead.overhead_pct"),
    "overload.innocent_completed_frac": (
        "bench_overload.json", "poison.innocent_completed_frac"),
}


def collect_bench_metrics(bench_dir: str) -> Dict[str, float]:
    """Flatten the bench artifacts in ``bench_dir`` into the gate's
    metric namespace. Metrics whose artifact (or field) is absent are
    simply omitted — the gate reports them as skipped, never invents a
    number."""
    out: Dict[str, float] = {}
    cache: Dict[str, Optional[dict]] = {}
    for metric, (fname, path) in BENCH_METRIC_SOURCES.items():
        if fname not in cache:
            p = os.path.join(bench_dir, fname)
            try:
                with open(p) as fh:
                    cache[fname] = json.load(fh)
            except (OSError, json.JSONDecodeError):
                cache[fname] = None
        art = cache[fname]
        if art is None:
            continue
        v = _dig(art, path)
        if v is not None:
            out[metric] = float(v)
    return out


def load_baseline(path: str) -> Optional[dict]:
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None


def compare_to_baseline(fresh: Dict[str, float],
                        baseline: Optional[dict]) -> dict:
    """The regression verdict. ``baseline["metrics"]`` rows pin
    ``{"value", "rel_tol", "direction"}`` per metric (direction
    ``"higher"`` = bigger is better). A fresh value worse than
    ``value * (1 - rel_tol)`` (or ``* (1 + rel_tol)`` for
    lower-is-better) is a FAILURE; absent fresh metrics are skipped
    (reported, not failed — a lane that didn't run a bench can't
    regress it)."""
    if not baseline or "metrics" not in baseline:
        return {"ok": True, "checked": 0,
                "note": "no baseline (benchmarks/perf_baseline.json "
                        "missing or empty) — gate skipped"}
    failures, checks, skipped = [], [], []
    for name, spec in baseline["metrics"].items():
        base = spec.get("value")
        if base is None:
            continue
        got = fresh.get(name)
        if got is None:
            skipped.append(name)
            continue
        tol = float(spec.get("rel_tol", 0.15))
        higher = spec.get("direction", "higher") == "higher"
        floor = base * (1.0 - tol)
        ceil = base * (1.0 + tol)
        ok = got >= floor if higher else got <= ceil
        row = {"metric": name, "baseline": base, "fresh": got,
               "rel_tol": tol, "direction": "higher" if higher else "lower",
               "bound": floor if higher else ceil,
               "delta_pct": round(100.0 * (got - base) / base, 2) if base
               else None,
               "ok": ok}
        checks.append(row)
        if not ok:
            failures.append(row)
    return {"ok": not failures, "checked": len(checks),
            "skipped": skipped, "failures": failures, "checks": checks}
