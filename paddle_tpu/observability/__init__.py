"""paddle_tpu.observability — the runtime's *metrics and tracing* half.

The profiler (``paddle_tpu.profiler``) answers "where did this step's
time go" with spans; this package answers the fleet questions — how
often the fused-conv Pallas path fired vs. fell back to XLA, how many
times each jitted entry point recompiled and for how long, what the
per-step tokens/s and device-memory watermarks were, and (since the
tracing half landed) what happened to EACH serving request — as cheap
always-on instruments with Prometheus/JSONL/Chrome-trace export.

Layout:
- ``metrics``:    thread-safe Counter/Gauge/Histogram/Summary registry
                  (lock-free writer hot path — a deque append, no lock
                  per op; Summary = streaming p50/p95/p99 over a
                  sliding sample window).
- ``exporters``:  Prometheus text exposition, JSONL snapshots, the
                  size-rotating JSONL sink (``RotatingJsonlSink``,
                  ``$PADDLE_TPU_SINK_DIR`` override), opt-in stdlib
                  http scrape endpoint (``start_http_server``).
- ``recompile``:  jax.monitoring compile listeners + ``entrypoint``
                  attribution + retrace warnings; compiles are ALSO
                  attributed into the active request trace.
- ``telemetry``:  ``StepTelemetry`` per-step records (step time, ips,
                  memory watermarks, compile deltas) feeding the hapi
                  callback and ``bench.py``; JSONL stream is rotation-
                  bounded.
- ``tracing``:    request-lifecycle spans/instants (default-on,
                  host-side only), Chrome-trace + JSONL export, the
                  flight-recorder ring + crash dumps, streaming
                  latency ``Digest``s.
- ``perf``:       per-executable cost/roofline attribution (XLA
                  ``cost_analysis``/``memory_analysis`` captured at
                  compile time, joined with measured entry timings
                  into MFU / achieved GB/s / roofline class), the HBM
                  ledger, OOM forensics dumps, and the
                  perf-regression-gate helpers.
- ``fleet``:      the fleet observability plane — traceparent
                  propagation helpers + catapult merge, the router-side
                  metric-federation aggregator, SLO burn-rate tracking
                  (``SLOConfig``/``SLOTracker``), and the robust
                  MAD straggler score.

Trace event schema (``tracing.events()`` rows / trace JSONL lines)::

    {"ph":   "X" (complete span) | "i" (instant),
     "name": span name — request lifecycle: request | queued |
             prefill | prefill_chunk | decode; instants: admitted |
             resume | first_token | prefix_cache_hit |
             prefix_cache_miss | cow_fork | preempted | requeued |
             completed | cancelled | expired | failed | rejected;
             engine: serving.step; generation: generation.prefill |
             generation.decode | generation.generate; compiles:
             xla_compile:<entry>,
     "cat":  request | engine | generation | compile | profiler,
     "trace": serving request id | "engine" | null,
     "tid":  recording OS thread ident,
     "ts_ns": monotonic perf_counter_ns start,
     "dur_ns": span duration (0 for instants),
     "args": optional small dict (slot, chunk range, block counts...)}

``chrome_trace()`` renders the same events as catapult JSON (one
swimlane per trace id; spans nest within the per-request ``request``
root span). ``GET /trace`` on the serving HTTP server serves it live.

``snapshot()`` is the one-call view of all of it — including the
serving gauges + block-pool stats (when an engine is alive) and the
tracing summary, so one snapshot captures the full system state.

Importing this package installs the jax.monitoring listeners (a list
append inside jax; per-event cost is one callback). ``disable()``
reduces every instrumentation site — metrics AND tracing — to a single
list-index check.
"""

from __future__ import annotations

import time

from . import exporters, fleet, metrics, perf, recompile, telemetry, tracing
from .exporters import (RotatingJsonlSink, parse_prometheus_text,
                        prometheus_text, render_families,
                        resolve_sink_path,
                        start_http_server, stop_http_server,
                        write_jsonl_snapshot)
from .fleet import (FleetMetricsAggregator, SLOConfig, SLOTracker,
                    attempt_trace_id, format_traceparent, mad_zscores,
                    merge_catapult, parse_traceparent)
from .metrics import (DEFAULT_BUCKETS, DEFAULT_QUANTILES, Counter, Gauge,
                      Histogram, MetricsRegistry, Summary, counter, gauge,
                      get_registry, histogram, summary)
from .metrics import _ENABLED
from .perf import (MEMORY_STATS_UNSUPPORTED, compare_to_baseline, dump_oom,
                   hbm_ledger, is_oom_error, ledger, peak_specs,
                   register_memory_component)
from .recompile import compile_events, current_entry, entry_stats, entrypoint
from .telemetry import StepTelemetry, memory_watermarks, step_records
from .tracing import (Digest, chrome_trace, disable_tracing, enable_tracing,
                      flight_dump, instant, register_state_provider, span,
                      trace_context, tracing_enabled)

__all__ = [
    "Counter", "Gauge", "Histogram", "Summary", "MetricsRegistry",
    "DEFAULT_BUCKETS", "DEFAULT_QUANTILES",
    "counter", "gauge", "histogram", "summary", "get_registry",
    "prometheus_text", "parse_prometheus_text", "render_families",
    "write_jsonl_snapshot",
    "start_http_server", "stop_http_server",
    "RotatingJsonlSink", "resolve_sink_path",
    "entrypoint", "current_entry", "compile_events", "entry_stats",
    "StepTelemetry", "memory_watermarks", "step_records",
    "tracing", "span", "instant", "trace_context", "chrome_trace",
    "flight_dump", "register_state_provider", "Digest",
    "enable_tracing", "disable_tracing", "tracing_enabled",
    "perf", "ledger", "hbm_ledger", "peak_specs", "is_oom_error",
    "dump_oom", "compare_to_baseline", "register_memory_component",
    "MEMORY_STATS_UNSUPPORTED",
    "fleet", "FleetMetricsAggregator", "SLOConfig", "SLOTracker",
    "attempt_trace_id", "format_traceparent", "parse_traceparent",
    "mad_zscores", "merge_catapult",
    "snapshot", "enable", "disable", "enabled",
]

# Recompile monitoring is the subsystem's reason to exist; subscribe as
# soon as the package is imported so no compile goes unattributed. Perf
# capture rides the same funnel (backend_compile wrapper + entrypoint
# call hook) — compile-time + host-side only, nothing on the dispatch
# fast path.
recompile.install()
perf.install()


def enable():
    _ENABLED[0] = True


def disable():
    """Kill switch: instrumentation sites reduce to one flag check."""
    _ENABLED[0] = False


def enabled() -> bool:
    return _ENABLED[0]


def _serving_state() -> dict:
    """The serving slice of a snapshot: every ``paddle_tpu_serving_*``
    / KV-block gauge currently registered (scrape-free), plus the live
    engine's ``stats()`` — queue, slots, block-pool accounting, prefix
    cache — via the flight-recorder state providers."""
    gauges = {}
    for m in get_registry().metrics():
        if m.kind != "gauge":
            continue
        if m.name.startswith(("paddle_tpu_serving_", "paddle_tpu_kv_")):
            samples = m.collect()
            if not m.labelnames:
                gauges[m.name] = samples[0]["value"] if samples else None
            else:
                gauges[m.name] = samples
    return {"gauges": gauges, **tracing.state_snapshot()}


def snapshot() -> dict:
    """Full observability state as one JSON-ready dict:

    - ``metrics``: every registered metric's samples (counters, gauges,
      histograms with bucket counts, summaries with quantiles),
    - ``compile_events``: the recent-compile flight recorder
      (entry, duration_s, ts),
    - ``entries``: per-entry-point call/compile/retrace totals,
    - ``steps``: the per-step telemetry ring (step time, ips, memory
      watermarks, compile deltas),
    - ``serving``: the serving gauges + (when an engine is alive) its
      full ``stats()`` incl. block-pool accounting — one call captures
      the whole system state, no scrape needed,
    - ``tracing``: span counts per phase, buffered-event count, last
      flight-dump path,
    - ``perf``: the per-executable cost/roofline ledger (flops, bytes,
      arithmetic intensity, MFU, roofline class), the HBM ledger
      (subsystem byte attribution + headroom), and the device peak
      table in force.
    """
    return {
        "ts": time.time(),
        "metrics": get_registry().collect(),
        "compile_events": compile_events(),
        "entries": entry_stats(),
        "steps": step_records(),
        "serving": _serving_state(),
        "tracing": tracing.summary(),
        "perf": perf.perf_snapshot(),
    }
