"""paddle_tpu.observability — the runtime's *metrics* half.

The profiler (``paddle_tpu.profiler``) answers "where did this step's
time go" with spans; this package answers the fleet questions — how
often the fused-conv Pallas path fired vs. fell back to XLA, how many
times each jitted entry point recompiled and for how long, what the
per-step tokens/s and device-memory watermarks were — as cheap
always-on counters with Prometheus/JSONL export.

Layout:
- ``metrics``:    thread-safe Counter/Gauge/Histogram registry (lock-free
                  writer hot path — a deque append, no lock per op).
- ``exporters``:  Prometheus text exposition, JSONL snapshots, opt-in
                  stdlib http scrape endpoint (``start_http_server``).
- ``recompile``:  jax.monitoring compile listeners + ``entrypoint``
                  attribution + retrace warnings.
- ``telemetry``:  ``StepTelemetry`` per-step records (step time, ips,
                  memory watermarks, compile deltas) feeding the hapi
                  callback and ``bench.py``.

``snapshot()`` is the one-call view of all of it.

Importing this package installs the jax.monitoring listeners (a list
append inside jax; per-event cost is one callback). ``disable()``
reduces every instrumentation site to a single list-index check.
"""

from __future__ import annotations

import time

from . import exporters, metrics, recompile, telemetry
from .exporters import (parse_prometheus_text, prometheus_text,
                        start_http_server, stop_http_server,
                        write_jsonl_snapshot)
from .metrics import (DEFAULT_BUCKETS, Counter, Gauge, Histogram,
                      MetricsRegistry, counter, gauge, get_registry,
                      histogram)
from .metrics import _ENABLED
from .recompile import compile_events, current_entry, entry_stats, entrypoint
from .telemetry import StepTelemetry, memory_watermarks, step_records

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "DEFAULT_BUCKETS",
    "counter", "gauge", "histogram", "get_registry",
    "prometheus_text", "parse_prometheus_text", "write_jsonl_snapshot",
    "start_http_server", "stop_http_server",
    "entrypoint", "current_entry", "compile_events", "entry_stats",
    "StepTelemetry", "memory_watermarks", "step_records",
    "snapshot", "enable", "disable", "enabled",
]

# Recompile monitoring is the subsystem's reason to exist; subscribe as
# soon as the package is imported so no compile goes unattributed.
recompile.install()


def enable():
    _ENABLED[0] = True


def disable():
    """Kill switch: instrumentation sites reduce to one flag check."""
    _ENABLED[0] = False


def enabled() -> bool:
    return _ENABLED[0]


def snapshot() -> dict:
    """Full observability state as one JSON-ready dict:

    - ``metrics``: every registered metric's samples (counters, gauges,
      histograms with bucket counts),
    - ``compile_events``: the recent-compile flight recorder
      (entry, duration_s, ts),
    - ``entries``: per-entry-point call/compile/retrace totals,
    - ``steps``: the per-step telemetry ring (step time, ips, memory
      watermarks, compile deltas).
    """
    return {
        "ts": time.time(),
        "metrics": get_registry().collect(),
        "compile_events": compile_events(),
        "entries": entry_stats(),
        "steps": step_records(),
    }
