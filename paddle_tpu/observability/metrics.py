"""Thread-safe metrics registry: Counter / Gauge / Histogram with labels.

The *metrics* half of the observability stack (the tracing half lives in
``paddle_tpu.profiler``). Reference analogue: the fleet's production
monitoring counters (Paddle exposes these through Profiler statistic
summaries and benchmark ips only; a serve-millions deployment needs the
Prometheus-shaped surface this module provides).

Hot-path contract (mirrors the profiler's ``_recording`` zero-cost
check): incrementing a counter or observing a histogram sample NEVER
takes a lock. Writers append the delta/sample to a ``collections.deque``
— ``deque.append`` is GIL-atomic, so concurrent increments are exact —
and readers (exporters, ``snapshot()``) fold the queue into the base
value under the metric's lock. When no exporter ever reads, a bounded
compaction (every ``_COMPACT_AT`` writes, amortized lock-free) keeps
memory flat. Instrumentation sites additionally guard on the module
flag ``_ENABLED[0]`` so the whole subsystem can be switched off to a
single list-index check per site.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "Summary", "MetricsRegistry",
    "get_registry", "counter", "gauge", "histogram", "summary",
    "DEFAULT_BUCKETS", "DEFAULT_QUANTILES",
]

# Zero-cost kill switch shared with the instrumentation sites (ops
# dispatch, conv/BN fusion peephole, watchdog): `if _ENABLED[0]:` is the
# whole cost when observability is disabled.
_ENABLED = [True]

# Writers self-compact once their pending queue reaches this length, so
# an unscraped process stays bounded: one (rare) lock every N writes.
_COMPACT_AT = 4096

# Prometheus-style duration buckets (seconds), tuned for the two
# populations we time: sub-ms op spans and multi-second XLA compiles.
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)

# Summary quantiles: the serving-latency trio (median + the two tails
# a latency SLO is written against).
DEFAULT_QUANTILES = (0.5, 0.95, 0.99)


class _CounterChild:
    __slots__ = ("_q", "_base", "_lock")

    # the pending deque is lock-free BY DESIGN (GIL-atomic appends);
    # only the folded base value needs the metric lock
    GUARDED_BY = {"_base": "_lock"}

    def __init__(self, lock: threading.Lock):
        self._q: deque = deque()
        self._base = 0.0
        self._lock = lock

    def inc(self, amount: float = 1.0):
        """Lock-free: one deque append (+ an int compare)."""
        self._q.append(amount)
        if len(self._q) >= _COMPACT_AT:
            self._compact()

    def _compact(self) -> float:
        with self._lock:
            q = self._q
            total = self._base
            while True:
                try:
                    total += q.popleft()
                except IndexError:
                    break
            self._base = total
            return total

    def value(self) -> float:
        return self._compact()


class _GaugeChild:
    """Gauges are read-side instruments (memory watermarks, ips) set at
    step granularity — ``set`` is a single atomic attribute store;
    inc/dec (rare) serialize on the metric lock."""

    __slots__ = ("_v", "_lock")

    GUARDED_BY = {"_v": "_lock"}

    def __init__(self, lock: threading.Lock):
        self._v = 0.0
        self._lock = lock

    def set(self, value: float):
        # pt-analysis: disable=lock-guarded-access -- the documented
        # lock-free gauge write: one GIL-atomic attribute store, no
        # read-modify-write to tear
        self._v = float(value)

    def inc(self, amount: float = 1.0):
        with self._lock:
            self._v += amount

    def dec(self, amount: float = 1.0):
        self.inc(-amount)

    def value(self) -> float:
        # pt-analysis: disable=lock-guarded-access -- GIL-atomic read of
        # a float attribute; gauge readers tolerate a stale value
        return self._v


class _HistogramChild:
    """``observe`` appends the raw sample (lock-free); bucketing happens
    at read/compaction time under the metric lock."""

    __slots__ = ("_q", "_counts", "_sum", "_count", "_buckets", "_lock")

    GUARDED_BY = {"_counts": "_lock", "_sum": "_lock", "_count": "_lock"}

    def __init__(self, lock: threading.Lock, buckets: Sequence[float]):
        self._q: deque = deque()
        self._buckets = tuple(buckets)
        self._counts = [0] * (len(self._buckets) + 1)  # +1: +Inf
        self._sum = 0.0
        self._count = 0
        self._lock = lock

    def observe(self, value: float):
        self._q.append(value)
        if len(self._q) >= _COMPACT_AT:
            self._compact()

    def _compact(self):
        with self._lock:
            q = self._q
            while True:
                try:
                    v = q.popleft()
                except IndexError:
                    break
                self._counts[bisect_left(self._buckets, v)] += 1
                self._sum += v
                self._count += 1
            return list(self._counts), self._sum, self._count

    def snapshot(self) -> Tuple[List[int], float, int]:
        """(per-bucket counts (non-cumulative, +Inf last), sum, count)."""
        return self._compact()

    def value(self) -> float:
        """Histogram "value" for generic readers: the running sum."""
        return self._compact()[1]


class _SummaryChild:
    """Streaming quantiles: a bounded ring of the most recent samples
    (``deque(maxlen)`` append — lock-free) with exact percentiles over
    the window computed at collect time. Same design as
    ``tracing.Digest``; kept separate so this module stays import-leaf."""

    __slots__ = ("_q", "_sum", "_count", "_quantiles", "_lock")

    GUARDED_BY = {"_sum": "_lock", "_count": "_lock"}

    def __init__(self, lock: threading.Lock, quantiles: Sequence[float],
                 window: int):
        self._q: deque = deque(maxlen=int(window))
        self._quantiles = tuple(quantiles)
        self._sum = 0.0
        self._count = 0
        self._lock = lock

    def observe(self, value: float):
        self._q.append(value)
        # count/sum are stats, not invariants: racing += may rarely drop
        # one under threads; the serving writers are single-threaded
        # pt-analysis: disable=lock-guarded-access -- the lock-free
        # observe hot path is the module contract (see the line above);
        # a dropped increment is an accepted stats-only error
        self._count += 1
        # pt-analysis: disable=lock-guarded-access -- same lock-free
        # observe contract as _count above
        self._sum += value

    def snapshot(self) -> Tuple[Dict[float, Optional[float]], float, int]:
        xs = sorted(self._q)

        def at(q):
            if not xs:
                return None
            pos = q * (len(xs) - 1)
            lo = int(pos)
            hi = min(lo + 1, len(xs) - 1)
            return xs[lo] + (xs[hi] - xs[lo]) * (pos - lo)

        # pt-analysis: disable=lock-guarded-access -- reader of the
        # racy-by-design stats pair; tolerances documented at observe
        return ({q: at(q) for q in self._quantiles}, self._sum, self._count)

    def quantile(self, q: float) -> Optional[float]:
        xs = sorted(self._q)
        if not xs:
            return None
        pos = q * (len(xs) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(xs) - 1)
        return xs[lo] + (xs[hi] - xs[lo]) * (pos - lo)

    def value(self) -> float:
        # pt-analysis: disable=lock-guarded-access -- same racy-by-design
        # stats reader as snapshot
        return self._sum


_CHILD_TYPES = {"counter": _CounterChild, "gauge": _GaugeChild,
                "histogram": _HistogramChild, "summary": _SummaryChild}


class _MetricBase:
    kind = "untyped"

    GUARDED_BY = {"_children": "_lock"}

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (), **kwargs):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._kwargs = kwargs
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        self._default = None if self.labelnames else self._make_child()

    def _make_child(self):
        raise NotImplementedError

    def labels(self, *values, **kv):
        if kv:
            if values:
                raise ValueError("pass label values positionally or by "
                                 "name, not both")
            values = tuple(str(kv[n]) for n in self.labelnames)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, got "
                f"{values}")
        # pt-analysis: disable=lock-guarded-access -- deliberate
        # double-checked fast path: dict.get is GIL-atomic and the
        # locked re-check below makes child creation race-free
        child = self._children.get(values)
        if child is None:
            with self._lock:
                child = self._children.get(values)
                if child is None:
                    child = self._make_child()
                    self._children[values] = child
        return child

    def _all_children(self) -> List[Tuple[Tuple[str, ...], object]]:
        if self._default is not None:
            return [((), self._default)]
        with self._lock:
            return list(self._children.items())

    # unlabeled convenience: metric acts as its own single child
    def _d(self):
        if self._default is None:
            raise ValueError(
                f"{self.name} has labels {self.labelnames}; call "
                f".labels(...) first")
        return self._default

    def collect(self) -> List[dict]:
        """Samples for exporters: [{labels: {...}, ...per-kind fields}]."""
        out = []
        for lv, child in self._all_children():
            labels = dict(zip(self.labelnames, lv))
            if isinstance(child, _HistogramChild):
                counts, s, c = child.snapshot()
                out.append({"labels": labels, "buckets": list(self.buckets),
                            "counts": counts, "sum": s, "count": c})
            elif isinstance(child, _SummaryChild):
                quantiles, s, c = child.snapshot()
                out.append({"labels": labels,
                            "quantiles": {str(q): v
                                          for q, v in quantiles.items()},
                            "sum": s, "count": c})
            else:
                out.append({"labels": labels, "value": child.value()})
        return out


class Counter(_MetricBase):
    kind = "counter"

    def _make_child(self):
        return _CounterChild(self._lock)

    def inc(self, amount: float = 1.0):
        self._d().inc(amount)

    def value(self) -> float:
        return self._d().value()


class Gauge(_MetricBase):
    kind = "gauge"

    def _make_child(self):
        return _GaugeChild(self._lock)

    def set(self, value: float):
        self._d().set(value)

    def inc(self, amount: float = 1.0):
        self._d().inc(amount)

    def dec(self, amount: float = 1.0):
        self._d().dec(amount)

    def value(self) -> float:
        return self._d().value()


class Histogram(_MetricBase):
    kind = "histogram"

    def __init__(self, name, help="", labelnames=(),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.buckets = tuple(sorted(buckets))
        super().__init__(name, help, labelnames)

    def _make_child(self):
        return _HistogramChild(self._lock, self.buckets)

    def observe(self, value: float):
        self._d().observe(value)

    def value(self) -> float:
        return self._d().value()


class Summary(_MetricBase):
    """Prometheus summary: streaming quantiles over a sliding sample
    window plus ``_sum``/``_count`` series. The serving latency digests
    (TTFT, TPOT, queue wait, prefill-chunk) are Summaries — tails
    (p95/p99) that a fixed histogram bucketing would quantize away."""

    kind = "summary"

    def __init__(self, name, help="", labelnames=(),
                 quantiles: Sequence[float] = DEFAULT_QUANTILES,
                 window: int = 4096):
        self.quantiles = tuple(sorted(quantiles))
        self.window = int(window)
        super().__init__(name, help, labelnames)

    def _make_child(self):
        return _SummaryChild(self._lock, self.quantiles, self.window)

    def observe(self, value: float):
        self._d().observe(value)

    def quantile(self, q: float) -> Optional[float]:
        return self._d().quantile(q)

    def value(self) -> float:
        return self._d().value()


class MetricsRegistry:
    """Name -> metric map; creation is idempotent (same name + kind
    returns the existing metric, so instrumentation sites can declare
    their metrics without import-order coupling)."""

    GUARDED_BY = {"_metrics": "_lock"}

    def __init__(self):
        self._metrics: Dict[str, _MetricBase] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        # pt-analysis: disable=lock-guarded-access -- deliberate
        # double-checked fast path (same discipline as labels());
        # creation re-checks under the lock below
        m = self._metrics.get(name)
        if m is not None:
            if not isinstance(m, cls) or m.labelnames != tuple(labelnames):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind} with "
                    f"labels {m.labelnames}")
            return m
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, labelnames, **kw)
                self._metrics[name] = m
            return m

    def counter(self, name, help="", labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(),
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def summary(self, name, help="", labelnames=(),
                quantiles=DEFAULT_QUANTILES, window: int = 4096) -> Summary:
        return self._get_or_create(Summary, name, help, labelnames,
                                   quantiles=quantiles, window=window)

    def get(self, name) -> Optional[_MetricBase]:
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> List[_MetricBase]:
        with self._lock:
            return list(self._metrics.values())

    def collect(self) -> Dict[str, dict]:
        """Full registry state: {name: {type, help, samples}}."""
        out = {}
        for m in self.metrics():
            out[m.name] = {"type": m.kind, "help": m.help,
                           "samples": m.collect()}
        return out

    def reset(self):
        """Drop all metrics (tests / fork-exec re-init)."""
        with self._lock:
            self._metrics.clear()


_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _registry


def counter(name, help="", labelnames=()) -> Counter:
    return _registry.counter(name, help, labelnames)


def gauge(name, help="", labelnames=()) -> Gauge:
    return _registry.gauge(name, help, labelnames)


def histogram(name, help="", labelnames=(), buckets=DEFAULT_BUCKETS) -> Histogram:
    return _registry.histogram(name, help, labelnames, buckets=buckets)


def summary(name, help="", labelnames=(), quantiles=DEFAULT_QUANTILES,
            window: int = 4096) -> Summary:
    return _registry.summary(name, help, labelnames, quantiles=quantiles,
                             window=window)
