"""Recompile monitor: attribute XLA compiles to jitted entry points.

jax 0.4.x emits ``jax.monitoring`` events around every trace/compile —
``/jax/core/compile/backend_compile_duration`` fires once per XLA
compilation with its wall seconds, and the compilation-cache events
(``/jax/compilation_cache/...``) mark cache traffic. This module
subscribes listeners once and attributes each compile to the *runtime
entry point* that triggered it: ``jit/api.py`` StaticFunction calls,
``generation.generate``, and the hapi ``Model`` train/eval steps wrap
their dispatch in ``entrypoint(name)``, which pushes the name onto a
thread-local stack the listener reads (compiles happen synchronously on
the dispatching thread).

Retrace detection (reference pain point: silent per-shape program
explosions): an entry point that compiles AFTER it has already completed
a call is retracing — new input shapes/dtypes or an unstable cache key.
Each such event increments ``paddle_tpu_retraces_total`` and logs a
one-line warning (per entry, first occurrence) so a shape regression in
a training loop is visible without a profiler run.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from . import metrics as _m

__all__ = ["install", "installed", "entrypoint", "current_entry",
           "compile_events", "total_compiles", "entry_stats", "reset_entries",
           "reset_warmup", "warmup_scope", "register_entry_location",
           "entry_location", "add_call_hook", "remove_call_hook"]

logger = logging.getLogger("paddle_tpu.observability")

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_UNATTRIBUTED = "<unattributed>"

_tls = threading.local()
_installed = [False]
_install_lock = threading.Lock()

# Bounded flight recorder of compile events (entry, event, duration_s, ts)
_events: deque = deque(maxlen=512)
# Per-entry call/compile bookkeeping for retrace detection
_entries: Dict[str, dict] = {}
_entries_lock = threading.Lock()
# entry name -> "file:line" of the jitted definition, so the retrace
# warning points at the source the static analyzer also reports on
_entry_locations: Dict[str, str] = {}
# completed-call hooks: fn(entry_name, wall_seconds) fired on every
# successful entrypoint exit — how the perf ledger joins each entry's
# static FLOPs/bytes with measured time. Empty list = zero clock reads.
_call_hooks: List = []


def add_call_hook(fn) -> None:
    """Register ``fn(entry, dt_s)`` to run when an entrypoint scope
    completes (idempotent). With no hooks registered the entrypoint
    takes no timestamps at all."""
    if fn not in _call_hooks:
        _call_hooks.append(fn)


def remove_call_hook(fn) -> None:
    try:
        _call_hooks.remove(fn)
    except ValueError:
        pass


def register_entry_location(name: str, fn=None,
                            location: Optional[str] = None) -> None:
    """Record where a jitted entry point is defined (``file:line``).
    Owners pass the callable (``StaticFunction``'s wrapped fn, the
    engine's local step/chunk defs) and the analyzer's resolver does the
    rest; an explicit ``location`` string overrides. Best-effort — a
    callable without source never raises."""
    if location is None and fn is not None:
        try:
            from ..analysis.resolver import source_location

            location = source_location(fn)
        except Exception:  # pragma: no cover — resolver must never break
            location = None
    if location:
        _entry_locations[name] = location


def entry_location(name: str) -> Optional[str]:
    return _entry_locations.get(name)

_compiles = _m.counter(
    "paddle_tpu_compiles_total",
    "XLA backend compilations attributed to the triggering entry point",
    ("entry",))
_compile_seconds = _m.histogram(
    "paddle_tpu_compile_seconds",
    "XLA backend compile wall time per entry point", ("entry",))
_retraces = _m.counter(
    "paddle_tpu_retraces_total",
    "compilations that happened AFTER an entry point had already "
    "completed a call (unexpected retrace: shape/dtype churn)", ("entry",))
_jax_events = _m.counter(
    "paddle_tpu_jax_monitoring_events_total",
    "raw jax.monitoring counter events (compilation cache traffic etc.)",
    ("event",))


def current_entry() -> str:
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else _UNATTRIBUTED


class entrypoint:
    """Context manager marking the currently-dispatching entry point so
    compile events attribute to it. Re-entrant; nesting attributes to the
    innermost entry. Completing the ``with`` block counts one call —
    the retrace detector's notion of "this entry is past warmup"."""

    __slots__ = ("name", "t0")

    def __init__(self, name: str):
        self.name = name
        self.t0 = None

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self.name)
        if _call_hooks:
            self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        _tls.stack.pop()
        if exc[0] is None:
            st = _entry_state(self.name)
            st["calls"] += 1
            if self.t0 is not None:
                dt = time.perf_counter() - self.t0
                for hook in _call_hooks:
                    try:
                        hook(self.name, dt)
                    except Exception:  # a perf hook must never break a call
                        logger.debug("entry call hook failed", exc_info=True)
        return False


class warmup_scope:
    """Mark the current thread as deliberately warming executables:
    compiles inside the scope are counted and attributed as usual but
    are NEVER retraces, regardless of the entry's completed-call count.

    ``reset_warmup`` covers the single-engine case (a fresh engine's
    entries start at calls == 0, so their first compiles are warmup by
    construction), but it cannot cover a SECOND in-process engine whose
    entries share names with one that already served calls — e.g. two
    serving replicas both dispatching ``serving.step``. Replica N+1's
    ``engine.warmup()`` runs inside this scope so its expected compiles
    don't trip the retrace alarm the router's zero-retrace invariant
    relies on. Re-entrant; thread-local (compiles run synchronously on
    the dispatching thread)."""

    __slots__ = ()

    def __enter__(self):
        _tls.warmup = getattr(_tls, "warmup", 0) + 1
        return self

    def __exit__(self, *exc):
        _tls.warmup -= 1
        return False


def _in_warmup_scope() -> bool:
    return getattr(_tls, "warmup", 0) > 0


def _entry_state(name: str) -> dict:
    st = _entries.get(name)
    if st is None:
        with _entries_lock:
            st = _entries.setdefault(
                name, {"calls": 0, "compiles": 0, "retraces": 0,
                       "compile_seconds": 0.0, "warned": False})
    return st


def _on_duration(name: str, duration: float, **kwargs):
    if not _m._ENABLED[0] or name != _COMPILE_EVENT:
        return
    try:
        entry = current_entry()
        _compiles.labels(entry).inc()
        _compile_seconds.labels(entry).observe(duration)
        _events.append({"entry": entry, "event": "backend_compile",
                        "duration_s": duration, "ts": time.time()})
        # attribute the compile into the active request trace (compiles
        # run synchronously on the dispatching thread, so the tracing
        # thread-local context is the request that paid for it)
        from . import tracing as _tracing

        _tracing._on_compile(entry, duration)
        st = _entry_state(entry)
        st["compiles"] += 1
        st["compile_seconds"] += duration
        if st["calls"] >= 1 and not _in_warmup_scope():
            st["retraces"] += 1
            _retraces.labels(entry).inc()
            if not st["warned"]:
                st["warned"] = True
                loc = _entry_locations.get(entry)
                logger.warning(
                    "unexpected retrace: entry %r%s recompiled (%.3fs) "
                    "after %d completed call(s) — input shapes/dtypes "
                    "changed or the jit cache key is unstable (compile "
                    "#%d)",
                    entry, f" (defined at {loc})" if loc else "",
                    duration, st["calls"], st["compiles"])
    except Exception:  # a metrics bug must never break a compile
        logger.debug("recompile monitor listener failed", exc_info=True)


def _on_event(name: str, **kwargs):
    if not _m._ENABLED[0] or not name.startswith("/jax/"):
        return
    try:
        _jax_events.labels(name).inc()
    except Exception:
        pass


def install() -> bool:
    """Register the jax.monitoring listeners (idempotent). Returns True
    when running with a jax that exposes the monitoring API."""
    if _installed[0]:
        return True
    with _install_lock:
        if _installed[0]:
            return True
        try:
            from jax import monitoring
        except Exception:
            return False
        monitoring.register_event_duration_secs_listener(_on_duration)
        monitoring.register_event_listener(_on_event)
        _installed[0] = True
        return True


def installed() -> bool:
    return _installed[0]


def compile_events() -> List[dict]:
    """The bounded flight recorder: most recent compiles, oldest first."""
    return list(_events)


def total_compiles() -> int:
    """Process-wide compile count (all entries) — cheap enough for the
    per-step telemetry delta."""
    return sum(st["compiles"] for st in list(_entries.values()))


def entry_stats() -> Dict[str, dict]:
    with _entries_lock:
        return {k: dict(v) for k, v in _entries.items()}


def reset_warmup(*names: str):
    """Restart retrace warmup for ``names``: the owner just built NEW
    jitted executables for those entries (e.g. a fresh ServingEngine's
    step/prefill closures), so their next compiles are expected warmup,
    not retraces. Compile/retrace totals are kept — only the completed-
    call count (the "past warmup" marker) and the warn latch clear."""
    with _entries_lock:
        for name in names:
            st = _entries.get(name)
            if st is not None:
                st["calls"] = 0
                st["warned"] = False


def reset_entries():
    """Clear attribution state + the event recorder (tests)."""
    with _entries_lock:
        _entries.clear()
    _events.clear()
