"""Fleet observability plane: the pieces that make the multi-replica
router read as ONE system instead of N disjoint processes.

Four mechanisms, each consumed by ``serving/router.py``:

- **Trace propagation** (the Dapper idea): the router derives a
  deterministic per-attempt trace id from ``(request id, attempt
  generation)`` and carries it across the replica boundary — as a
  W3C-traceparent-style header on ``HTTPReplica``'s ``POST /generate``,
  or through the existing thread-local ``tracing.trace_context`` for
  ``LocalReplica``. The replica-side ``Request`` adopts the propagated
  id as its trace, so its whole span tree (queued → prefill → decode →
  terminal) lands under an id the router can fetch back and merge.
  Each retry/hedge gets a DISTINCT id (the generation is in it), so a
  failover request renders as one catapult file with one swimlane per
  attempt. Malformed or absent headers parse to ``None`` — a hostile
  header means a fresh local trace, never an error.

- **Metric federation** (the Monarch/Prometheus-federation idea):
  ``FleetMetricsAggregator`` caches each replica's ``/metrics``
  exposition (scraped by the router on its staleness-bounded stats
  cadence), relabels every series with ``replica=<name>`` (an existing
  ``replica`` label is preserved as ``exported_replica``, the
  honor-labels convention), and renders the union plus fleet roll-ups
  under ``replica="fleet"``: counters and histogram buckets sum,
  summary quantiles merge count-weighted (an approximation — exact
  distributed quantiles need sketches; the count weighting keeps a
  busy replica from being averaged away by an idle one), and the
  goodput gauge sums (fleet goodput IS the sum; other gauges —
  utilizations, depths — are left per-replica where summing would
  lie). A hung scrape keeps serving the last-known series with a
  ``paddle_tpu_fleet_scrape_stale`` marker — staleness is visible,
  never an ejection.

- **SLO tracking**: ``SLOConfig`` declares the latency contract (TTFT
  p95 bound, deadline-met goodput floor, availability target) and
  ``SLOTracker`` evaluates it as multi-window burn rates in the SRE-
  workbook style: ``burn = bad_fraction / error_budget`` over a fast
  (default 1 min) and a slow (default 30 min) window, and an objective
  is breached only when BOTH windows burn above their thresholds — the
  fast window makes alerts responsive, the slow window keeps a
  transient blip from paging. Windows and thresholds are knobs so the
  test clock can compress them.

- **Straggler detection**: ``mad_zscores`` is the robust modified
  z-score (0.6745 · (x − median) / MAD, the LossSpikeSentinel idiom;
  mean-absolute-deviation fallback when MAD degenerates to 0) the
  router applies to per-replica TPOT p50s — a replica whose decode
  cadence sits far above the fleet median is flagged ``straggler``
  without any absolute latency threshold to mis-tune.

- **SLO-driven brownout**: ``BrownoutController`` closes the loop the
  ``SLOTracker`` leaves open — when BOTH burn windows run hot it steps
  the serving plane through a declarative degradation ladder (shed
  batch-class work → disable hedging → cap batch decode length →
  shrink speculation), one level per burning report with a minimum
  dwell, and walks back down only after a streak of consecutive
  healthy reports (hysteresis: a single good minute must not re-admit
  the load that caused the burn). Every transition is a counter, a
  gauge move, and a traced instant — brownout is an OPERATED state,
  never a silent one.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from . import metrics as _m
from . import tracing as _tracing
from .exporters import parse_prometheus_text, render_families

__all__ = [
    "TRACEPARENT_HEADER",
    "attempt_trace_id", "format_traceparent", "parse_traceparent",
    "traceparent_of", "merge_catapult",
    "FleetMetricsAggregator", "FLEET_REPLICA_LABEL",
    "SLOConfig", "SLOTracker",
    "BrownoutController", "BROWNOUT_LEVELS",
    "mad_zscores",
]

# ---------------------------------------------------------------------------
# trace propagation (W3C traceparent subset)
# ---------------------------------------------------------------------------

TRACEPARENT_HEADER = "traceparent"

_TRACE_HEX = 32   # 16-byte trace id, lowercase hex
_PARENT_HEX = 16  # 8-byte parent/span id, lowercase hex


def attempt_trace_id(request_id: int, attempt_gen: int) -> str:
    """The propagated trace id for one router attempt:
    ``<32-hex trace>-<16-hex parent>``. The trace half is the router
    request id, the parent half the attempt generation — deterministic,
    collision-free per attempt, and distinct per retry/hedge so each
    attempt renders as its own swimlane."""
    t = (int(request_id) + 1) & ((1 << 128) - 1)  # +1: all-zero is invalid
    p = int(attempt_gen) & ((1 << 64) - 1)
    return f"{t or 1:0{_TRACE_HEX}x}-{p or 1:0{_PARENT_HEX}x}"


def format_traceparent(trace_hex: str, parent_hex: str) -> str:
    """``00-<trace>-<parent>-01`` (version 00, sampled flag)."""
    return f"00-{trace_hex}-{parent_hex}-01"


def traceparent_of(trace_id: str) -> Optional[str]:
    """The header value carrying an ``attempt_trace_id`` — None when
    the id isn't in the propagated shape (never raises)."""
    parts = str(trace_id).split("-")
    if len(parts) != 2:
        return None
    t, p = parts
    if len(t) != _TRACE_HEX or len(p) != _PARENT_HEX:
        return None
    return format_traceparent(t, p)


def _is_hex(s: str) -> bool:
    return bool(s) and all(c in "0123456789abcdef" for c in s)


def parse_traceparent(value) -> Optional[str]:
    """Parse a traceparent header into the propagated trace id
    (``<trace>-<parent>``), or None for anything malformed: wrong
    version, wrong field count/width, uppercase or non-hex digits,
    all-zero ids, non-string input. NEVER raises — a hostile header
    must cost a fresh local trace, not a 400/500."""
    if not isinstance(value, str):
        return None
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace, parent, flags = parts
    if version != "00" or len(flags) != 2 or not _is_hex(flags):
        return None
    if len(trace) != _TRACE_HEX or not _is_hex(trace) \
            or trace == "0" * _TRACE_HEX:
        return None
    if len(parent) != _PARENT_HEX or not _is_hex(parent) \
            or parent == "0" * _PARENT_HEX:
        return None
    return f"{trace}-{parent}"


def merge_catapult(parts: Sequence[Tuple[str, dict]]) -> dict:
    """Merge several chrome-trace (catapult) dicts into one multi-
    swimlane file: each part becomes its own process (pid = part
    index) named by its label, so the router's lane and every
    attempt's replica-side lane sit side by side on the shared
    monotonic clock. Input dicts are not mutated."""
    out: List[dict] = []
    for pid, (label, ct) in enumerate(parts):
        named = False
        for ev in (ct or {}).get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = pid
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                if named:
                    continue  # one process_name per lane group
                named = True
                ev["args"] = {"name": label}
            out.append(ev)
        if not named:
            out.append({"name": "process_name", "ph": "M", "pid": pid,
                        "tid": 0, "args": {"name": label}})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# metric federation
# ---------------------------------------------------------------------------

FLEET_REPLICA_LABEL = "fleet"  # roll-up series carry replica="fleet"

_fleet_scrapes_total = _m.counter(
    "paddle_tpu_fleet_scrapes_total",
    "replica /metrics scrapes by the router-side federation aggregator",
    ("outcome",))
_federated_series = _m.gauge(
    "paddle_tpu_fleet_federated_series",
    "series in the last federated /metrics exposition (union of every "
    "replica's relabeled series plus the fleet roll-ups)")

# gauges where a fleet sum is the truthful roll-up (rates/throughputs);
# utilization/depth gauges stay per-replica — summing them would lie
_ROLLUP_GAUGES = frozenset({
    "paddle_tpu_serving_goodput_tokens_per_second",
})


def _group_key(series: str, labels: Dict[str, str]) -> tuple:
    rest = tuple(sorted((k, v) for k, v in labels.items()
                        if k not in ("replica", "exported_replica")))
    return series, rest


class FleetMetricsAggregator:
    """Router-side cache of per-replica Prometheus expositions.

    ``should_scrape`` enforces the staleness bound (and claims the
    refresh window even when the scrape then fails, so a hung replica
    is retried on the cadence, not hammered); ``update``/``mark_stale``
    record the outcome; ``federated_families``/``render`` produce the
    union + roll-ups. Thread-safe: the router's driver threads scrape
    while the HTTP thread renders."""

    def __init__(self):
        self._lock = threading.Lock()
        # name -> {"ts", "families", "stale", "ever"}
        self._scraped: Dict[str, dict] = {}
        self.scrapes = 0
        self.scrape_errors = 0

    # -- scrape bookkeeping --------------------------------------------------
    def should_scrape(self, name: str, now: float, refresh_s: float) -> bool:
        with self._lock:
            e = self._scraped.setdefault(
                name, {"ts": 0.0, "families": {}, "stale": False,
                       "ever": False})
            if e["ever"] and now - e["ts"] <= refresh_s:
                return False
            e["ts"] = now  # claim the window even if the scrape fails
            e["ever"] = True
            return True

    def update(self, name: str, text: str, now: Optional[float] = None):
        families = parse_prometheus_text(text)
        with self._lock:
            e = self._scraped.setdefault(
                name, {"ts": 0.0, "families": {}, "stale": False,
                       "ever": True})
            e["families"] = families
            e["stale"] = False
            if now is not None:
                e["ts"] = now
            self.scrapes += 1
        _fleet_scrapes_total.labels("ok").inc()

    def mark_stale(self, name: str):
        """A scrape failed/timed out: keep the last-known series,
        flagged stale — visibility degrades, rotation does not."""
        with self._lock:
            e = self._scraped.get(name)
            if e is not None:
                e["stale"] = True
            self.scrape_errors += 1
        _fleet_scrapes_total.labels("error").inc()

    def forget(self, name: str):
        with self._lock:
            self._scraped.pop(name, None)

    # -- federation ----------------------------------------------------------
    def federated_families(self) -> Dict[str, dict]:
        """The union of every replica's families, each sample relabeled
        ``replica=<name>``, plus the ``replica="fleet"`` roll-ups."""
        with self._lock:
            snap = {n: e["families"] for n, e in self._scraped.items()
                    if e["families"]}
        fams: Dict[str, dict] = {}
        for replica in sorted(snap):
            for fname, fam in snap[replica].items():
                dst = fams.setdefault(
                    fname, {"type": fam.get("type", "untyped"),
                            "help": fam.get("help", ""), "samples": []})
                if not dst["help"] and fam.get("help"):
                    dst["help"] = fam["help"]
                for s in fam["samples"]:
                    labels = dict(s["labels"])
                    if "replica" in labels:
                        labels["exported_replica"] = labels.pop("replica")
                    labels["replica"] = replica
                    dst["samples"].append({"series": s["series"],
                                           "labels": labels,
                                           "value": s["value"]})
        for fname, fam in fams.items():
            fam["samples"].extend(self._rollup(fname, fam))
        return fams

    def _rollup(self, fname: str, fam: dict) -> List[dict]:
        kind = fam["type"]
        if kind == "summary":
            return self._rollup_summary(fname, fam)
        if kind not in ("counter", "histogram") \
                and fname not in _ROLLUP_GAUGES:
            return []
        sums: Dict[tuple, float] = {}
        for s in fam["samples"]:
            key = _group_key(s["series"], s["labels"])
            sums[key] = sums.get(key, 0.0) + s["value"]
        return [{"series": series,
                 "labels": {**dict(rest), "replica": FLEET_REPLICA_LABEL},
                 "value": v}
                for (series, rest), v in sums.items()]

    def _rollup_summary(self, fname: str, fam: dict) -> List[dict]:
        """Count-weighted summary merge: quantiles average weighted by
        each replica's ``_count`` (approximate by construction),
        ``_sum``/``_count`` sum exactly."""
        # group by the label set minus replica/quantile
        groups: Dict[tuple, dict] = {}
        for s in fam["samples"]:
            labels = dict(s["labels"])
            replica = labels.pop("replica", "")
            labels.pop("exported_replica", None)
            q = labels.pop("quantile", None)
            key = tuple(sorted(labels.items()))
            g = groups.setdefault(key, {"labels": labels, "counts": {},
                                        "sums": {}, "quantiles": {}})
            if s["series"] == fname + "_count":
                g["counts"][replica] = s["value"]
            elif s["series"] == fname + "_sum":
                g["sums"][replica] = s["value"]
            elif q is not None:
                g["quantiles"].setdefault(q, {})[replica] = s["value"]
        out: List[dict] = []
        for g in groups.values():
            base = {**g["labels"], "replica": FLEET_REPLICA_LABEL}
            total = sum(g["counts"].values())
            for q, per_rep in sorted(g["quantiles"].items()):
                w = [(v, g["counts"].get(rep, 0.0))
                     for rep, v in per_rep.items()]
                wsum = sum(c for _, c in w)
                if wsum <= 0:
                    continue
                merged = sum(v * c for v, c in w) / wsum
                out.append({"series": fname,
                            "labels": {**base, "quantile": q},
                            "value": merged})
            out.append({"series": fname + "_sum", "labels": dict(base),
                        "value": sum(g["sums"].values())})
            out.append({"series": fname + "_count", "labels": dict(base),
                        "value": total})
        return out

    def render(self) -> str:
        """The federated exposition text (what router ``GET /metrics``
        serves), including the scrape-health families."""
        fams = self.federated_families()
        now = time.perf_counter()
        with self._lock:
            health = [(n, e["ts"], e["stale"])
                      for n, e in sorted(self._scraped.items()) if e["ever"]]
        if health:
            fams["paddle_tpu_fleet_scrape_age_seconds"] = {
                "type": "gauge",
                "help": "seconds since the replica's /metrics was last "
                        "scraped (claimed window start on failures)",
                "samples": [{"series": "paddle_tpu_fleet_scrape_age_seconds",
                             "labels": {"replica": n},
                             "value": round(max(now - ts, 0.0), 3)}
                            for n, ts, _ in health]}
            fams["paddle_tpu_fleet_scrape_stale"] = {
                "type": "gauge",
                "help": "1 while the replica's federated series are "
                        "last-known values from before a failed scrape",
                "samples": [{"series": "paddle_tpu_fleet_scrape_stale",
                             "labels": {"replica": n},
                             "value": 1 if stale else 0}
                            for n, _, stale in health]}
        n_series = sum(len(f["samples"]) for f in fams.values())
        _federated_series.set(n_series)
        return render_families(fams)

    def stats(self) -> dict:
        with self._lock:
            replicas = {
                n: {"stale": e["stale"],
                    "families": len(e["families"]),
                    "series": sum(len(f["samples"])
                                  for f in e["families"].values())}
                for n, e in self._scraped.items() if e["ever"]}
        return {"replicas": replicas, "scrapes": self.scrapes,
                "scrape_errors": self.scrape_errors}


# ---------------------------------------------------------------------------
# SLO burn-rate tracking
# ---------------------------------------------------------------------------

_slo_burn = _m.gauge(
    "paddle_tpu_slo_burn_rate",
    "error-budget burn rate per objective and window (1.0 = consuming "
    "budget exactly at the sustainable rate)", ("objective", "window"))
_slo_ok = _m.gauge(
    "paddle_tpu_slo_ok",
    "1 while the objective is within its multi-window burn-rate "
    "thresholds (0 = both windows burning too hot)", ("objective",))


@dataclass
class SLOConfig:
    """The fleet's declarative latency contract.

    Targets are good-event fractions: ``ttft_target_fraction`` of
    requests must see first token within ``ttft_p95_s`` (the "p95
    bound" shape), ``goodput_floor`` must complete within their
    deadline, ``availability`` must not FAIL. The error budget of each
    objective is ``1 - target``; burn rate is the windowed bad-fraction
    divided by that budget. ``fast``/``slow`` windows + thresholds are
    the SRE-workbook multi-window convention (defaults 1 min at 14.4x
    / 30 min at 1.0x), sized down by tests to fit the test clock."""

    ttft_p95_s: float = 1.0
    ttft_target_fraction: float = 0.95
    goodput_floor: float = 0.95
    availability: float = 0.99
    fast_window_s: float = 60.0
    slow_window_s: float = 1800.0
    fast_burn_threshold: float = 14.4
    slow_burn_threshold: float = 1.0
    history: int = 65536  # retained observations (bounded memory)

    def __post_init__(self):
        for name in ("ttft_target_fraction", "goodput_floor",
                     "availability"):
            v = getattr(self, name)
            if not 0.0 < v < 1.0:
                raise ValueError(f"{name} must be in (0, 1): an SLO of "
                                 f"{v} has no error budget to burn")
        if self.fast_window_s <= 0 or self.slow_window_s <= 0:
            raise ValueError("SLO windows must be positive")
        if self.fast_window_s > self.slow_window_s:
            raise ValueError("fast_window_s must not exceed slow_window_s")


class SLOTracker:
    """Sliding-window burn-rate evaluation over terminal request
    observations. ``observe`` is called by the router as each request
    finishes; ``report`` evaluates every objective over both windows
    (and publishes the ``paddle_tpu_slo_*`` gauges)."""

    def __init__(self, config: Optional[SLOConfig] = None,
                 clock=time.perf_counter):
        self.config = config or SLOConfig()
        self._clock = clock
        self._lock = threading.Lock()
        from collections import deque
        # (ts, available, goodput_ok, ttft_ok-or-None)
        self._obs = deque(maxlen=int(self.config.history))
        self.observed = 0
        self._last_publish = 0.0

    def observe(self, status: str, ttft_s: Optional[float],
                met_deadline: bool, ts: Optional[float] = None):
        """One terminal request. ``cancelled`` requests are excluded
        from every objective (a caller hanging up is not a fleet
        failure); requests that never produced a first token are
        excluded from the TTFT objective only."""
        if status == "cancelled":
            return
        now = ts if ts is not None else self._clock()
        rec = (now,
               status != "failed",
               bool(met_deadline),
               None if ttft_s is None
               else ttft_s <= self.config.ttft_p95_s)
        with self._lock:
            self._obs.append(rec)
            self.observed += 1
        # keep the gauges fresh without paying a full report per finish
        if now - self._last_publish >= 0.5:
            self._last_publish = now
            self.report(now=now)

    def report(self, now: Optional[float] = None) -> dict:
        cfg = self.config
        if now is None:
            now = self._clock()
        with self._lock:
            obs = list(self._obs)
        objectives = {}
        overall_ok = True
        for name, target, good in (
                ("availability", cfg.availability, lambda o: o[1]),
                ("goodput", cfg.goodput_floor, lambda o: o[2]),
                ("ttft_p95", cfg.ttft_target_fraction, lambda o: o[3])):
            budget = 1.0 - target
            windows = {}
            breached = {}
            for wname, wsec, thr in (
                    ("fast", cfg.fast_window_s, cfg.fast_burn_threshold),
                    ("slow", cfg.slow_window_s, cfg.slow_burn_threshold)):
                rel = [good(o) for o in obs if now - o[0] <= wsec]
                rel = [g for g in rel if g is not None]
                total = len(rel)
                bad = sum(1 for g in rel if not g)
                frac = bad / total if total else 0.0
                burn = frac / budget
                windows[wname] = {"window_s": wsec, "total": total,
                                  "bad": bad,
                                  "bad_fraction": round(frac, 6),
                                  "burn_rate": round(burn, 4),
                                  "threshold": thr}
                breached[wname] = total > 0 and burn >= thr
                _slo_burn.labels(name, wname).set(burn)
            # multi-window rule: alert only when BOTH windows burn hot
            ok = not (breached["fast"] and breached["slow"])
            _slo_ok.labels(name).set(1 if ok else 0)
            objectives[name] = {"target": target,
                                "error_budget": round(budget, 6),
                                "windows": windows, "ok": ok}
            overall_ok = overall_ok and ok
        return {
            "ok": overall_ok,
            "observed": self.observed,
            "config": {"ttft_p95_s": cfg.ttft_p95_s,
                       "ttft_target_fraction": cfg.ttft_target_fraction,
                       "goodput_floor": cfg.goodput_floor,
                       "availability": cfg.availability,
                       "fast_window_s": cfg.fast_window_s,
                       "slow_window_s": cfg.slow_window_s},
            "objectives": objectives,
        }


# ---------------------------------------------------------------------------
# SLO-driven brownout
# ---------------------------------------------------------------------------

_brownout_level = _m.gauge(
    "paddle_tpu_brownout_level",
    "current degradation level (0 = normal; higher = more load shed "
    "to protect the interactive SLO)")
_brownout_transitions = _m.counter(
    "paddle_tpu_brownout_transitions_total",
    "brownout ladder transitions", ("direction",))

# the degradation ladder, mildest first. Each level IMPLIES every level
# below it: at "cap_batch_tokens" the fleet is also shedding batch and
# not hedging. The ordering is goodput-per-cost: shed the work whose
# deadline tolerates a retry first, spend compile-cache-warm capacity
# (spec) last.
BROWNOUT_LEVELS = (
    "normal",            # 0: no degradation
    "shed_batch",        # 1: reject batch-class submits at the router
    "no_hedge",          # 2: stop duplicating slow attempts
    "cap_batch_tokens",  # 3: clamp batch-class max_new_tokens
    "shrink_spec",       # 4: cap speculation width (verify FLOPs back)
)


class BrownoutController:
    """Hysteresis ladder from SLO burn to degradation actions.

    Feed it ``SLOTracker.report()`` dicts on a fixed cadence (the
    router's probe loop). When a report is unhealthy (``ok`` False —
    both burn windows hot on some objective) the controller escalates
    ONE level, at most once per ``min_dwell_s``; when
    ``recover_reports`` consecutive healthy reports arrive it
    de-escalates one level (again dwell-limited). Asymmetry is the
    point: escalation needs one bad report because budget is burning
    NOW; recovery needs a streak because re-admitting load on a single
    good sample re-triggers the burn (the classic overload-control
    flap). Action predicates (``shed_batch`` etc.) are what the
    router/engine consult inline — reading them is lock-free-cheap and
    allocation-free."""

    GUARDED_BY = {"_level": "_lock", "_streak": "_lock",
                  "_last_move": "_lock", "_transitions": "_lock"}

    def __init__(self, recover_reports: int = 3,
                 min_dwell_s: float = 2.0, max_level: int = None,
                 clock=time.perf_counter):
        if recover_reports < 1:
            raise ValueError("recover_reports must be >= 1")
        top = len(BROWNOUT_LEVELS) - 1
        self.recover_reports = int(recover_reports)
        self.min_dwell_s = float(min_dwell_s)
        self.max_level = top if max_level is None else min(int(max_level),
                                                           top)
        self._clock = clock
        self._lock = threading.Lock()
        self._level = 0
        self._streak = 0           # consecutive healthy reports
        self._last_move = -1e18    # so the first escalation is immediate
        self._transitions = deque(maxlen=64)  # (ts, from, to, direction)
        _brownout_level.set(0)

    # -- the control loop ----------------------------------------------------
    def update(self, slo_report: Optional[dict],
               now: Optional[float] = None) -> int:
        """One control tick. Returns the (possibly new) level."""
        if now is None:
            now = self._clock()
        healthy = bool(slo_report.get("ok", True)) if slo_report else True
        # an SLO report with nothing observed is vacuously healthy —
        # browning out an idle fleet would be pure self-harm
        if slo_report and not slo_report.get("observed"):
            healthy = True
        with self._lock:
            if not healthy:
                self._streak = 0
                if self._level < self.max_level \
                        and now - self._last_move >= self.min_dwell_s:
                    self._move(self._level + 1, "escalate", now,
                               slo_report)
            else:
                self._streak += 1
                if self._level > 0 \
                        and self._streak >= self.recover_reports \
                        and now - self._last_move >= self.min_dwell_s:
                    self._streak = 0
                    self._move(self._level - 1, "recover", now, slo_report)
            return self._level

    # holds-lock: _lock
    def _move(self, new_level: int, direction: str, now: float,
              slo_report: Optional[dict]):
        """Caller holds the lock."""
        old = self._level
        self._level = new_level
        self._last_move = now
        self._transitions.append(
            {"ts": round(now, 3), "from": BROWNOUT_LEVELS[old],
             "to": BROWNOUT_LEVELS[new_level], "direction": direction})
        _brownout_level.set(new_level)
        _brownout_transitions.labels(direction).inc()
        burning = []
        if slo_report:
            burning = [n for n, o in
                       slo_report.get("objectives", {}).items()
                       if not o.get("ok", True)]
        _tracing.instant(
            "brownout_" + direction, cat="brownout", trace="brownout",
            args={"from": BROWNOUT_LEVELS[old],
                  "to": BROWNOUT_LEVELS[new_level],
                  "burning": burning})

    # -- action predicates (what the serving plane consults inline) ---------
    @property
    def level(self) -> int:
        with self._lock:
            return self._level

    @property
    def level_name(self) -> str:
        return BROWNOUT_LEVELS[self.level]

    @property
    def shed_batch(self) -> bool:
        """Level >= 1: reject batch-class work at the router door."""
        return self.level >= 1

    @property
    def hedge_disabled(self) -> bool:
        """Level >= 2: a hedge is a deliberate duplicate — the first
        capacity to reclaim after shedding deferrable work."""
        return self.level >= 2

    @property
    def cap_batch_tokens(self) -> bool:
        """Level >= 3: batch work that DID get in decodes short."""
        return self.level >= 3

    @property
    def shrink_spec(self) -> bool:
        """Level >= 4: cap spec_k — verify-bundle FLOPs back to decode."""
        return self.level >= 4

    def report(self) -> dict:
        with self._lock:
            return {
                "level": self._level,
                "level_name": BROWNOUT_LEVELS[self._level],
                "levels": list(BROWNOUT_LEVELS),
                "max_level": self.max_level,
                "healthy_streak": self._streak,
                "recover_reports": self.recover_reports,
                "min_dwell_s": self.min_dwell_s,
                "actions": {
                    "shed_batch": self._level >= 1,
                    "hedge_disabled": self._level >= 2,
                    "cap_batch_tokens": self._level >= 3,
                    "shrink_spec": self._level >= 4,
                },
                "transitions": list(self._transitions),
            }


# ---------------------------------------------------------------------------
# straggler scoring
# ---------------------------------------------------------------------------


def mad_zscores(values: Sequence[float]) -> List[float]:
    """Modified (robust) z-scores: ``0.6745 * (x - median) / MAD``.
    When the MAD degenerates to 0 (most values identical — the common
    fleet case of N twins and one straggler), falls back to the mean
    absolute deviation with the matching 0.7979 consistency constant
    (Iglewicz & Hoaglin); all-identical input scores all zeros."""
    xs = sorted(values)
    n = len(xs)
    if n == 0:
        return []
    med = (xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2]))
    devs = sorted(abs(v - med) for v in values)
    mad = (devs[n // 2] if n % 2 else 0.5 * (devs[n // 2 - 1]
                                             + devs[n // 2]))
    if mad > 0:
        return [0.6745 * (v - med) / mad for v in values]
    mean_ad = sum(devs) / n
    if mean_ad > 0:
        return [0.7979 * (v - med) / mean_ad for v in values]
    return [0.0 for _ in values]
