"""Per-step training telemetry: one record per optimizer step.

``StepTelemetry`` fuses three existing signals into a single stream:
the wall step time (its own clock, or fed by the profiler's
``_Benchmark`` ips timer via ``attach_benchmark``), the PJRT device
memory watermarks (``memory_stats()`` live/peak bytes — absent on some
CPU transports, recorded as an explicit ``"memory": "unsupported"``
marker, never as 0-valued gauges), and the recompile monitor's compile
count (per-step delta, so a mid-training retrace shows up on exactly the
step that paid for it). Each record lands in a bounded in-process ring
(surfaced by ``observability.snapshot()``) and, when a path is given,
as one JSONL line per step — the stream ``bench.py`` and the hapi
``TelemetryCallback`` emit so BENCH numbers come from telemetry instead
of ad-hoc prints.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Optional, Tuple

from . import metrics as _m
from . import recompile as _rc

__all__ = ["StepTelemetry", "memory_watermarks", "record_memory_gauges",
           "step_records", "clear_step_records"]

# Process-wide ring of step records from every StepTelemetry instance;
# snapshot() exposes it, run_shards merges it across shard processes.
_STEP_RECORDS: deque = deque(maxlen=2048)

_step_seconds = _m.histogram(
    "paddle_tpu_step_seconds", "training/eval step wall time", ("entry",),
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
             5.0, 10.0, 30.0, 60.0))
_ips_gauge = _m.gauge(
    "paddle_tpu_ips", "items (samples/tokens) per second, latest step",
    ("entry",))
_live_bytes = _m.gauge(
    "paddle_tpu_device_live_bytes",
    "device bytes in use at the last recorded step")
_peak_bytes = _m.gauge(
    "paddle_tpu_device_peak_bytes",
    "device peak bytes in use (process high-water mark)")
_steps_total = _m.counter(
    "paddle_tpu_steps_total", "telemetry-recorded steps", ("entry",))


def memory_watermarks() -> Tuple[Optional[int], Optional[int]]:
    """(live_bytes, peak_bytes) summed over devices via PJRT
    ``memory_stats()``; (None, None) where the transport doesn't report
    (CPU PJRT commonly returns nothing)."""
    try:
        import jax

        live = peak = None
        for dev in jax.local_devices():
            try:
                stats = dev.memory_stats() or {}
            except Exception:
                continue
            if "bytes_in_use" in stats:
                live = (live or 0) + int(stats["bytes_in_use"])
            if "peak_bytes_in_use" in stats:
                peak = (peak or 0) + int(stats["peak_bytes_in_use"])
        return live, peak
    except Exception:
        return None, None


def record_memory_gauges() -> Tuple[Optional[int], Optional[int]]:
    """Read the watermarks AND publish them to the device-memory gauges
    (the Profiler's profile_memory hook and StepTelemetry both use
    this). An unsupported transport — (None, None) — must NOT write
    0-valued gauges (a dashboard would read "no memory in use"); the
    gauges stay untouched and the JSONL stream carries the explicit
    ``unsupported`` marker instead."""
    live, peak = memory_watermarks()
    if live is not None:
        _live_bytes.set(live)
    if peak is not None:
        _peak_bytes.set(peak)
    return live, peak


class StepTelemetry:
    """Per-step recorder.

    st = StepTelemetry(entry="train", jsonl_path="steps.jsonl")
    loop: work; st.step(num_samples=batch)      # or tokens=batch*seq
    st.close()

    ``step()`` cost when idle-configured: a perf_counter read, a
    memory_stats call, and a handful of deque appends — safe to leave on
    in production loops (the reference ips timer already pays the clock
    read).

    The JSONL stream is bounded: ``max_bytes`` (keep-1 rotation to
    ``<path>.1``) caps the file a long serving/training run can grow,
    and a relative ``jsonl_path`` lands in ``$PADDLE_TPU_SINK_DIR``
    when that override is set (see ``exporters.RotatingJsonlSink``)."""

    def __init__(self, entry: str = "train", jsonl_path: Optional[str] = None,
                 record_memory: bool = True, max_bytes: int = 64 << 20):
        self.entry = entry
        self.jsonl_path = jsonl_path
        self.record_memory = record_memory
        self.max_bytes = int(max_bytes)
        self._sink = None
        self._idx = 0
        self._last = time.perf_counter()
        self._compiles_seen = _rc.total_compiles()
        self._bench = None

    # -- feeding ------------------------------------------------------------
    def step(self, num_samples: Optional[int] = None,
             tokens: Optional[int] = None,
             step_time: Optional[float] = None,
             extra: Optional[dict] = None) -> dict:
        """Record one step. ``step_time`` overrides the internal clock
        (used when fed by the profiler benchmark timer)."""
        now = time.perf_counter()
        dt = step_time if step_time is not None else now - self._last
        self._last = now
        n = tokens if tokens is not None else num_samples
        ips = (n / dt) if (n and dt > 0) else ((1.0 / dt) if dt > 0 else None)
        compiles = _rc.total_compiles()
        rec = {
            "entry": self.entry, "step": self._idx, "ts": time.time(),
            "step_time_s": dt,
            "ips": ips,
            "unit": "tokens" if tokens is not None else "samples",
            "compile_count_delta": compiles - self._compiles_seen,
        }
        if num_samples is not None or tokens is not None:
            rec["num_items"] = n
        if self.record_memory:
            live, peak = record_memory_gauges()
            if live is None and peak is None:
                # transport reports nothing: say so explicitly instead
                # of emitting null byte fields a downstream aggregator
                # would coerce to 0 (poisoning min/mean over the stream)
                from .perf import MEMORY_STATS_UNSUPPORTED

                rec["memory"] = MEMORY_STATS_UNSUPPORTED
            else:
                rec["live_bytes"] = live
                rec["peak_bytes"] = peak
        if extra:
            rec.update(extra)
        self._compiles_seen = compiles
        self._idx += 1

        _steps_total.labels(self.entry).inc()
        _step_seconds.labels(self.entry).observe(dt)
        if ips is not None:
            _ips_gauge.labels(self.entry).set(ips)
        _STEP_RECORDS.append(rec)
        if self.jsonl_path:
            if self._sink is None:
                from .exporters import RotatingJsonlSink

                self._sink = RotatingJsonlSink(self.jsonl_path,
                                               max_bytes=self.max_bytes)
            self._sink.write(rec)
        return rec

    def mark(self):
        """Reset the step clock without recording (start of a timed
        window: excludes setup/warmup from the first step's time)."""
        self._last = time.perf_counter()
        self._compiles_seen = _rc.total_compiles()

    # -- profiler benchmark-timer integration --------------------------------
    def attach_benchmark(self):
        """Feed this recorder from the existing profiler ips timer
        (``profiler._Benchmark``): every ``benchmark().step(n)`` forwards
        its measured step time + sample count here, so a loop already
        instrumented with the reference-shaped timer gets telemetry for
        free. Detach with ``detach_benchmark``."""
        from .. import profiler as _prof

        _prof._telemetry_sink[0] = self
        self._bench = _prof
        self.mark()
        return self

    def detach_benchmark(self):
        if self._bench is not None:
            self._bench._telemetry_sink[0] = None
            self._bench = None

    # -- results -------------------------------------------------------------
    def records(self):
        return [r for r in list(_STEP_RECORDS) if r["entry"] == self.entry]

    def close(self):
        self.detach_benchmark()
        if self._sink is not None:
            self._sink.close()
            self._sink = None

    def __enter__(self):
        self.mark()
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def step_records():
    return list(_STEP_RECORDS)


def clear_step_records():
    _STEP_RECORDS.clear()
