"""Metric exporters: Prometheus text exposition, JSONL snapshots, a
size-rotating JSONL sink, and an opt-in stdlib ``http.server`` scrape
endpoint.

The Prometheus text format follows the exposition spec (``# HELP`` /
``# TYPE`` headers, escaped HELP text (``\\`` and ``\\n``) and label
values (``\\``, ``"``, ``\\n``), cumulative histogram buckets with an
explicit ``+Inf`` le plus ``_sum``/``_count`` series, summary quantile
series). ``parse_prometheus_text`` is the matching reader — used by
the round-trip test and by anyone scraping the JSONL lane without a
real Prometheus.

Sinks: every file-appending exporter (``StepTelemetry`` JSONL, trace
JSONL, flight dumps) resolves RELATIVE paths against the
``PADDLE_TPU_SINK_DIR`` env var when set (one knob moves every
artifact off a read-only cwd), and ``RotatingJsonlSink`` bounds them —
``max_bytes`` with keep-1 rotation, so a long serving run cannot grow
a telemetry file without bound.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from .metrics import MetricsRegistry, get_registry

__all__ = [
    "prometheus_text", "parse_prometheus_text", "render_families",
    "write_jsonl_snapshot",
    "start_http_server", "stop_http_server",
    "RotatingJsonlSink", "resolve_sink_path",
]

SINK_DIR_ENV = "PADDLE_TPU_SINK_DIR"


def resolve_sink_path(path: str) -> str:
    """Relative sink paths land in ``$PADDLE_TPU_SINK_DIR`` when set
    (created on demand); absolute paths and unset env pass through."""
    sink_dir = os.environ.get(SINK_DIR_ENV)
    if sink_dir and not os.path.isabs(path):
        os.makedirs(sink_dir, exist_ok=True)
        return os.path.join(sink_dir, path)
    return path


class RotatingJsonlSink:
    """Append-one-JSON-line-per-record sink with size-based rotation:
    when the file would exceed ``max_bytes``, it is renamed to
    ``<path>.1`` (replacing the previous rotation — keep-1) and a fresh
    file is started, so total disk use is bounded at ~2x max_bytes."""

    def __init__(self, path: str, max_bytes: int = 64 << 20):
        self.path = resolve_sink_path(path)
        self.max_bytes = int(max_bytes)
        self._fh = None
        self._size = 0

    def write(self, rec: dict):
        line = json.dumps(rec) + "\n"
        if self._fh is None:
            self._fh = open(self.path, "a")
            self._size = self._fh.tell()
        if self._size and self._size + len(line) > self.max_bytes:
            self._fh.close()
            os.replace(self.path, self.path + ".1")
            self._fh = open(self.path, "a")
            self._size = 0
        self._fh.write(line)
        self._fh.flush()
        self._size += len(line)

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    # exposition spec: HELP text escapes backslash and newline (a raw
    # newline here would corrupt the whole exposition — every following
    # fragment would parse as a sample line)
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _unescape_help(v: str) -> str:
    out, i = [], 0
    while i < len(v):
        if v[i] == "\\" and i + 1 < len(v):
            out.append({"n": "\n", "\\": "\\"}.get(v[i + 1], v[i + 1]))
            i += 2
        else:
            out.append(v[i])
            i += 1
    return "".join(out)


def _fmt_labels(labels: Dict[str, str], extra: Optional[Dict[str, str]] = None) -> str:
    items = dict(labels)
    if extra:
        items.update(extra)
    if not items:
        return ""
    inner = ",".join(f'{k}="{_escape_label(str(v))}"'
                     for k, v in sorted(items.items()))
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    f = float(v)
    return repr(int(f)) if f.is_integer() and abs(f) < 2 ** 53 else repr(f)


def prometheus_text(registry: Optional[MetricsRegistry] = None) -> str:
    """Render the registry in Prometheus text exposition format."""
    reg = registry or get_registry()
    lines: List[str] = []
    for m in sorted(reg.metrics(), key=lambda m: m.name):
        lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        for sample in m.collect():
            labels = sample["labels"]
            if m.kind == "summary":
                for q, v in sample["quantiles"].items():
                    if v is None:
                        continue
                    lines.append(
                        f"{m.name}{_fmt_labels(labels, {'quantile': q})}"
                        f" {_fmt_value(v)}")
                lines.append(f"{m.name}_sum{_fmt_labels(labels)}"
                             f" {_fmt_value(sample['sum'])}")
                lines.append(f"{m.name}_count{_fmt_labels(labels)}"
                             f" {sample['count']}")
            elif m.kind == "histogram":
                cum = 0
                for le, c in zip(sample["buckets"], sample["counts"]):
                    cum += c
                    lines.append(
                        f"{m.name}_bucket"
                        f"{_fmt_labels(labels, {'le': _fmt_value(le)})}"
                        f" {cum}")
                cum += sample["counts"][-1]
                lines.append(f"{m.name}_bucket"
                             f"{_fmt_labels(labels, {'le': '+Inf'})} {cum}")
                lines.append(f"{m.name}_sum{_fmt_labels(labels)}"
                             f" {_fmt_value(sample['sum'])}")
                lines.append(f"{m.name}_count{_fmt_labels(labels)}"
                             f" {sample['count']}")
            else:
                lines.append(f"{m.name}{_fmt_labels(labels)}"
                             f" {_fmt_value(sample['value'])}")
    return "\n".join(lines) + "\n"


def _parse_labels(s: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    i = 0
    while i < len(s):
        eq = s.index("=", i)
        name = s[i:eq].strip().lstrip(",").strip()
        assert s[eq + 1] == '"', f"malformed label set: {s!r}"
        j = eq + 2
        buf = []
        while s[j] != '"':
            if s[j] == "\\":
                nxt = s[j + 1]
                buf.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, nxt))
                j += 2
            else:
                buf.append(s[j])
                j += 1
        out[name] = "".join(buf)
        i = j + 1
    return out


def parse_prometheus_text(text: str) -> Dict[str, dict]:
    """Parse the exposition format back into
    {name: {type, help, samples: [{labels, value}]}} — sample names keep
    their ``_bucket``/``_sum``/``_count`` suffixes (series-level view),
    grouped under the declared family name."""
    families: Dict[str, dict] = {}
    types: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            help_text = _unescape_help(help_text)
            families.setdefault(name, {"type": "untyped", "help": help_text,
                                       "samples": []})
            families[name]["help"] = help_text
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            families.setdefault(name, {"type": kind, "help": "",
                                       "samples": []})
            families[name]["type"] = kind
            types[name] = kind
            continue
        if line.startswith("#"):
            continue
        if "{" in line:
            name = line[:line.index("{")]
            rest = line[line.index("{") + 1:]
            labels_s, _, value_s = rest.rpartition("} ")
            labels = _parse_labels(labels_s)
        else:
            name, _, value_s = line.rpartition(" ")
            labels = {}
        value = float(value_s)
        family = name
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[:-len(suffix)] if name.endswith(suffix) else None
            if base and types.get(base) in ("histogram", "summary"):
                family = base
                break
        families.setdefault(family, {"type": "untyped", "help": "",
                                     "samples": []})
        families[family]["samples"].append(
            {"series": name, "labels": labels, "value": value})
    return families


def render_families(families: Dict[str, dict]) -> str:
    """Inverse of ``parse_prometheus_text``: render a family dict back
    to exposition text. Families are emitted name-sorted with their
    ``# HELP``/``# TYPE`` headers (so the declared kind — notably
    ``summary`` — survives a parse → render → parse round trip);
    samples keep their insertion order and any ``_bucket``/``_sum``/
    ``_count`` suffixes already baked into ``series``. This is the
    fleet-federation writer: the router parses each replica's
    exposition, relabels/rolls up, and renders the union with this."""
    lines: List[str] = []
    for name in sorted(families):
        fam = families[name]
        if fam.get("help"):
            lines.append(f"# HELP {name} {_escape_help(fam['help'])}")
        lines.append(f"# TYPE {name} {fam.get('type') or 'untyped'}")
        for s in fam.get("samples", ()):
            lines.append(f"{s['series']}{_fmt_labels(s.get('labels', {}))}"
                         f" {_fmt_value(s['value'])}")
    return "\n".join(lines) + "\n"


def write_jsonl_snapshot(path: str, registry: Optional[MetricsRegistry] = None,
                         extra: Optional[dict] = None):
    """Append ONE JSON line holding the full registry state (plus any
    ``extra`` fields) — the flight-recorder export: a file of these lines
    is a coarse time series a fleet log pipeline can ingest directly."""
    reg = registry or get_registry()
    rec = {"ts": time.time(), "metrics": reg.collect()}
    if extra:
        rec.update(extra)
    with open(path, "a") as fh:
        fh.write(json.dumps(rec) + "\n")
    return rec


# ---------------------------------------------------------------------------
# Opt-in scrape endpoint (stdlib http.server; no third-party deps)
# ---------------------------------------------------------------------------

_server = None
_server_thread = None
_server_lock = threading.Lock()


def start_http_server(port: int = 0, addr: str = "127.0.0.1"):
    """Serve ``/metrics`` (Prometheus text) and ``/snapshot`` (JSON) on a
    daemon thread. Returns the bound port (``port=0`` picks a free one).
    Opt-in only: nothing in the runtime starts this implicitly."""
    global _server, _server_thread
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            code = 200
            if self.path.split("?")[0] == "/metrics":
                body = prometheus_text().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif self.path.split("?")[0] == "/snapshot":
                from . import snapshot

                body = json.dumps(snapshot()).encode()
                ctype = "application/json"
            elif self.path.split("?")[0] == "/healthz":
                # liveness + the serving gauges (queue depth, slot
                # occupancy), so a probe sees serving state without
                # pulling a full snapshot
                reg = get_registry()

                def _g(name):
                    m = reg.get(name)
                    return m.value() if m is not None else None

                unhealthy = _g("paddle_tpu_serving_engine_unhealthy")
                code = 503 if unhealthy else 200
                body = json.dumps({
                    "status": "unhealthy" if unhealthy else "ok",
                    "ts": time.time(),
                    "serving_queue_depth": _g("paddle_tpu_serving_queue_depth"),
                    "serving_slots_busy": _g("paddle_tpu_serving_slots_busy"),
                    "serving_slot_occupancy": _g(
                        "paddle_tpu_serving_slot_occupancy"),
                    "serving_engine_crashes": _g(
                        "paddle_tpu_serving_engine_crashes_total"),
                }).encode()
                ctype = "application/json"
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # no per-scrape stderr chatter
            pass

    with _server_lock:
        if _server is not None:
            return _server.server_address[1]
        _server = ThreadingHTTPServer((addr, port), _Handler)
        _server_thread = threading.Thread(target=_server.serve_forever,
                                          name="paddle-tpu-metrics",
                                          daemon=True)
        _server_thread.start()
        return _server.server_address[1]


def stop_http_server():
    global _server, _server_thread
    with _server_lock:
        if _server is not None:
            _server.shutdown()
            _server.server_close()
            _server = None
            _server_thread = None
