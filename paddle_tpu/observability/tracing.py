"""Request-lifecycle tracing: low-overhead spans/instants, a bounded
flight-recorder ring, and streaming latency digests.

The third leg of the observability stack: the profiler answers "where
did this STEP's time go" (host/device spans around one training step),
the metrics registry answers "what is the runtime doing over time"
(counters/gauges), and this module answers "what happened to THIS
request" — the per-iteration timeline Orca/vLLM-class serving systems
treat as the primary operational tool. The serving engine threads
spans through the whole request lifecycle (queued → admitted → prefill
chunks → decode windows → terminal), the recompile monitor attributes
XLA compiles into the active trace, and ``generation.generate`` marks
its prefill/decode phases.

Hot-path contract (the metrics registry's discipline, applied to
events): recording a span or instant NEVER takes a lock — it is one
``perf_counter_ns`` read (or zero, when the caller already holds the
timestamps) plus a ``deque.append`` into a per-thread buffer.
Per-thread buffers self-compact into the global bounded ring every
``_COMPACT_AT`` events (one amortized lock), and readers (exporters,
the flight recorder) drain them under the same lock. Tracing is
DEFAULT-ON: the measured overhead on ``bench_serving.py`` is the <2%
acceptance number, and everything here is host-side only — no traced
value ever sees an event, so the one-step-compile invariant holds with
tracing enabled. ``PADDLE_TPU_TRACING=0`` (or ``disable_tracing()``)
reduces every site to a single list-index check.

Event schema (what ``events()`` returns and the JSONL export writes,
one JSON object per line):

- ``ph``:     ``"X"`` (complete span) or ``"i"`` (instant event)
- ``name``:   span/event name (``queued``, ``prefill_chunk``, ...)
- ``cat``:    category (``request``, ``engine``, ``generation``,
              ``compile``, ``profiler``)
- ``trace``:  trace id — the serving request id for request-lifecycle
              events, ``"engine"`` for pool-wide engine events, or
              null for unattributed events
- ``tid``:    OS thread ident of the recording thread
- ``ts_ns``:  monotonic start time (``time.perf_counter_ns`` — the
              same clock the Request timestamps use)
- ``dur_ns``: span duration (0 for instants)
- ``args``:   optional dict of small JSON-ready values

``chrome_trace()`` renders the same events as Chrome-trace (catapult)
JSON — one synthetic thread lane per trace id, so loading ``/trace``
in chrome://tracing or Perfetto shows each request as its own swimlane
with nested spans.

The **flight recorder** is the ring itself: ``flight_dump(reason)``
writes the last-N events plus every registered state provider's
snapshot (the serving engine registers ``engine.stats()``, which
carries the block-pool accounting) to one JSON file. It is wired to
the engine crash path, ``PoolExhaustedError`` escaping the step loop,
and the fault-tolerance SIGTERM/SIGINT handler — the post-mortem for
"what was the engine doing when it died".
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import weakref
from collections import deque
from typing import Any, Dict, List, Optional

from . import metrics as _m

__all__ = [
    "tracing_enabled", "enable_tracing", "disable_tracing",
    "span", "begin_span", "end_span", "instant", "complete",
    "trace_context", "current_trace",
    "events", "clear", "chrome_trace", "export_chrome_trace",
    "export_jsonl", "span_counts", "summary",
    "Digest",
    "flight_dump", "last_flight_dump", "register_state_provider",
    "unregister_state_provider", "state_snapshot",
    "attach_profiler_spans", "detach_profiler_spans",
]

logger = logging.getLogger("paddle_tpu.observability")

# Kill switch (single list-index check per site, like metrics._ENABLED;
# observability.disable() gates this too — both flags must be up).
_TRACING = [os.environ.get("PADDLE_TPU_TRACING", "1") != "0"]

# Per-thread buffers self-compact into the ring at this length.
_COMPACT_AT = 512

# The bounded flight-recorder ring: most recent events, process-wide.
_RING_CAPACITY = int(os.environ.get("PADDLE_TPU_TRACE_RING", "16384"))

_lock = threading.Lock()
_ring: deque = deque(maxlen=_RING_CAPACITY)
_tls = threading.local()
# [(weakref-to-thread, buffer)] — registered once per thread (under
# _lock); pruned when the thread is gone and its buffer drained.
_buffers: List[tuple] = []
# total events ever recorded per (ph, name) — survives ring eviction,
# feeds the CI trace summary (span counts per phase)
_counts: Dict[str, int] = {}

_events_total = _m.counter(
    "paddle_tpu_trace_events_total",
    "trace events recorded (spans + instants), by category", ("cat",))
_flight_dumps = _m.counter(
    "paddle_tpu_flight_dumps_total",
    "flight-recorder dumps written, by trigger reason", ("reason",))

_last_dump_path: List[Optional[str]] = [None]


def tracing_enabled() -> bool:
    return _TRACING[0] and _m._ENABLED[0]


def enable_tracing():
    _TRACING[0] = True


def disable_tracing():
    """Reduce every tracing site to one list-index check."""
    _TRACING[0] = False


# ---------------------------------------------------------------------------
# recording (the lock-free hot path)
# ---------------------------------------------------------------------------


def _buf() -> deque:
    b = getattr(_tls, "buf", None)
    if b is None:
        b = _tls.buf = deque()
        t = threading.current_thread()
        with _lock:
            _buffers.append((weakref.ref(t), b))
    return b


def _record(ph: str, name: str, cat: str, trace, tid: int, ts_ns: int,
            dur_ns: int, args):
    b = _buf()
    b.append((ph, name, cat, trace, tid, ts_ns, dur_ns, args))
    if len(b) >= _COMPACT_AT:
        _flush_locked()


def _flush_locked():
    """Drain every thread's buffer into the bounded ring (and the
    per-name totals); prune buffers whose threads are gone."""
    with _lock:
        dead = []
        for i, (tref, b) in enumerate(_buffers):
            while True:
                try:
                    ev = b.popleft()
                except IndexError:
                    break
                _ring.append(ev)
                key = ev[1]
                _counts[key] = _counts.get(key, 0) + 1
                _events_total.labels(ev[2]).inc()
            if tref() is None:
                dead.append(i)
        for i in reversed(dead):
            del _buffers[i]


# ---------------------------------------------------------------------------
# trace-context propagation (thread-local)
# ---------------------------------------------------------------------------


def current_trace():
    """The active trace id on this thread (set by ``trace_context``),
    or None. Compile events and nested spans attribute to it."""
    stack = getattr(_tls, "trace", None)
    return stack[-1] if stack else None


class trace_context:
    """Mark ``trace_id`` as the active trace on this thread for the
    duration of the ``with`` block (re-entrant; innermost wins)."""

    __slots__ = ("trace_id",)

    def __init__(self, trace_id):
        self.trace_id = trace_id

    def __enter__(self):
        stack = getattr(_tls, "trace", None)
        if stack is None:
            stack = _tls.trace = []
        stack.append(self.trace_id)
        return self

    def __exit__(self, *exc):
        _tls.trace.pop()
        return False


# ---------------------------------------------------------------------------
# spans + instants
# ---------------------------------------------------------------------------


class _Span:
    """An open span handle: begun on one call site (possibly one
    thread), ended on another — how the cross-iteration lifecycle spans
    (``queued``, ``decode``) are recorded."""

    __slots__ = ("name", "cat", "trace", "tid", "t0", "args", "_open")

    def __init__(self, name, cat, trace, tid, t0, args):
        self.name = name
        self.cat = cat
        self.trace = trace
        self.tid = tid
        self.t0 = t0
        self.args = args
        self._open = True


def begin_span(name: str, cat: str = "", trace=None, args=None,
               ts_ns: Optional[int] = None) -> Optional[_Span]:
    """Open a span; returns a handle for ``end_span`` (None when
    tracing is off — ``end_span(None)`` is a no-op, so call sites need
    no guards)."""
    if not tracing_enabled():
        return None
    if trace is None:
        trace = current_trace()
    return _Span(name, cat, trace, threading.get_ident(),
                 ts_ns if ts_ns is not None else time.perf_counter_ns(),
                 args)


def end_span(sp: Optional[_Span], ts_ns: Optional[int] = None, args=None):
    """Close an open span and record it as one complete event (idempotent
    — a span already ended, e.g. by ``Request.finish``, is skipped)."""
    if sp is None or not sp._open:
        return
    sp._open = False
    if not tracing_enabled():
        return
    t1 = ts_ns if ts_ns is not None else time.perf_counter_ns()
    a = sp.args
    if args:
        a = {**(a or {}), **args}
    _record("X", sp.name, sp.cat, sp.trace, sp.tid, sp.t0,
            max(t1 - sp.t0, 0), a)


class span:
    """Lexical span context manager::

        with tracing.span("generation.prefill", cat="generation"):
            ...
    """

    __slots__ = ("_sp", "name", "cat", "trace", "args")

    def __init__(self, name: str, cat: str = "", trace=None, args=None):
        self.name = name
        self.cat = cat
        self.trace = trace
        self.args = args
        self._sp = None

    def __enter__(self):
        self._sp = begin_span(self.name, self.cat, self.trace, self.args)
        return self._sp

    def __exit__(self, *exc):
        end_span(self._sp)
        return False


def instant(name: str, cat: str = "", trace=None, args=None,
            ts_ns: Optional[int] = None):
    """Record a zero-duration event (prefix-cache hit, COW fork,
    preemption, completion...)."""
    if not tracing_enabled():
        return
    if trace is None:
        trace = current_trace()
    _record("i", name, cat, trace, threading.get_ident(),
            ts_ns if ts_ns is not None else time.perf_counter_ns(), 0, args)


def complete(name: str, cat: str, trace, ts_ns: int, dur_ns: int, args=None):
    """Record an already-measured span from existing timestamps — zero
    extra clock reads (the engine's step loop already timed itself)."""
    if not tracing_enabled():
        return
    _record("X", name, cat, trace, threading.get_ident(), ts_ns,
            max(dur_ns, 0), args)


# ---------------------------------------------------------------------------
# reading + export
# ---------------------------------------------------------------------------


def _to_dict(ev: tuple) -> dict:
    ph, name, cat, trace, tid, ts, dur, args = ev
    out = {"ph": ph, "name": name, "cat": cat, "trace": trace, "tid": tid,
           "ts_ns": ts, "dur_ns": dur}
    if args:
        out["args"] = args
    return out


def events(trace=None, name: Optional[str] = None) -> List[dict]:
    """All buffered events (ring + live thread buffers), oldest first;
    optionally filtered to one trace id and/or one event name."""
    _flush_locked()
    with _lock:
        evs = list(_ring)
    if trace is not None:
        evs = [e for e in evs if e[3] == trace]
    if name is not None:
        evs = [e for e in evs if e[1] == name]
    evs.sort(key=lambda e: e[5])
    return [_to_dict(e) for e in evs]


def clear():
    """Drop every buffered event + the per-name totals (tests)."""
    _flush_locked()
    with _lock:
        _ring.clear()
        _counts.clear()


def span_counts() -> Dict[str, int]:
    """Total events ever recorded per name — NOT bounded by the ring,
    so CI span-count summaries survive long runs."""
    _flush_locked()
    with _lock:
        return dict(_counts)


def summary() -> dict:
    """JSON-ready tracing summary for ``observability.snapshot()`` and
    the run_shards telemetry lane."""
    counts = span_counts()
    with _lock:
        buffered = len(_ring)
    return {
        "enabled": tracing_enabled(),
        "ring_capacity": _RING_CAPACITY,
        "events_buffered": buffered,
        "events_recorded": sum(counts.values()),
        "span_counts": counts,
        "last_flight_dump": _last_dump_path[0],
    }


def chrome_trace(trace=None) -> dict:
    """Render buffered events as Chrome-trace (catapult) JSON: one
    synthetic thread lane per trace id (``request <id>`` /
    ``engine`` / ``untraced``), spans as ``"X"`` complete events in
    microseconds, instants as thread-scoped ``"i"`` events. Loadable in
    chrome://tracing and Perfetto; merge-compatible with the profiler's
    ``export_chrome_tracing`` output (same ``traceEvents`` shape)."""
    evs = events(trace)
    pid = os.getpid()
    lanes: Dict[Any, int] = {}
    out = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": "paddle_tpu trace"}}]

    def lane(tr) -> int:
        if tr not in lanes:
            lanes[tr] = len(lanes)
            if tr is None:
                lname = "untraced"
            elif isinstance(tr, int):
                lname = f"request {tr}"
            else:
                lname = str(tr)
            out.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": lanes[tr], "args": {"name": lname}})
        return lanes[tr]

    for e in evs:
        rec = {"name": e["name"], "cat": e["cat"] or "event", "ph": e["ph"],
               "pid": pid, "tid": lane(e["trace"]),
               "ts": e["ts_ns"] / 1000.0}
        if e["ph"] == "X":
            rec["dur"] = e["dur_ns"] / 1000.0
        else:
            rec["s"] = "t"
        if "args" in e:
            rec["args"] = e["args"]
        out.append(rec)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def export_chrome_trace(path: str, trace=None) -> str:
    """Write ``chrome_trace()`` JSON to ``path`` (relative paths land
    in the ``PADDLE_TPU_SINK_DIR`` override, like every other sink)."""
    from .exporters import resolve_sink_path

    path = resolve_sink_path(path)
    with open(path, "w") as fh:
        json.dump(chrome_trace(trace), fh)
    return path


def export_jsonl(path: str, trace=None, max_bytes: int = 64 << 20) -> str:
    """Append every buffered event as one JSON line each, through the
    size-rotating sink (``max_bytes``, keep-1)."""
    from .exporters import RotatingJsonlSink

    sink = RotatingJsonlSink(path, max_bytes=max_bytes)
    try:
        for e in events(trace):
            sink.write(e)
    finally:
        sink.close()
    return sink.path


# ---------------------------------------------------------------------------
# streaming percentile digests
# ---------------------------------------------------------------------------


class Digest:
    """Streaming p50/p95/p99: a bounded ring of the most recent
    ``window`` samples (``deque.append`` — the lock-free writer path)
    with exact percentiles computed over the window at read time.
    Within the window this is EXACTLY ``numpy.percentile`` (method
    'linear'); beyond it, a sliding-window quantile — the operational
    behavior a latency dashboard wants anyway (old traffic ages out)."""

    __slots__ = ("_q", "count", "sum")

    def __init__(self, window: int = 4096):
        self._q: deque = deque(maxlen=int(window))
        self.count = 0
        self.sum = 0.0

    def observe(self, value: float):
        self._q.append(value)
        self.count += 1
        self.sum += value

    def quantile(self, q: float) -> Optional[float]:
        xs = sorted(self._q)
        if not xs:
            return None
        # numpy's default 'linear' interpolation
        pos = q * (len(xs) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(xs) - 1)
        return xs[lo] + (xs[hi] - xs[lo]) * (pos - lo)

    def percentiles(self) -> dict:
        xs = sorted(self._q)

        def at(q):
            if not xs:
                return None
            pos = q * (len(xs) - 1)
            lo = int(pos)
            hi = min(lo + 1, len(xs) - 1)
            return xs[lo] + (xs[hi] - xs[lo]) * (pos - lo)

        return {"p50": at(0.50), "p95": at(0.95), "p99": at(0.99),
                "count": self.count,
                "mean": (self.sum / self.count) if self.count else None}


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

_providers: Dict[str, Any] = {}
_providers_lock = threading.Lock()


def register_state_provider(name: str, fn):
    """Register a zero-arg callable whose return value (a JSON-ready
    dict, or None to be skipped) is captured in every flight dump and
    in ``state_snapshot()``. The serving engine registers a weakref'd
    ``engine.stats`` here, so dumps carry pool/slot/queue state."""
    with _providers_lock:
        _providers[name] = fn


def unregister_state_provider(name: str):
    with _providers_lock:
        _providers.pop(name, None)


def state_snapshot() -> dict:
    """Every registered provider's current state ({} when none). A
    provider that raises contributes its error instead of killing the
    dump — the flight recorder must never be the second crash."""
    with _providers_lock:
        items = list(_providers.items())
    out = {}
    for name, fn in items:
        try:
            state = fn()
        except Exception as e:  # noqa: BLE001 — dump must survive
            state = {"error": repr(e)}
        if state is not None:
            out[name] = state
    return out


def last_flight_dump() -> Optional[str]:
    return _last_dump_path[0]


def flight_dump(reason: str, extra: Optional[dict] = None,
                path: Optional[str] = None, last_n: int = 4096) -> Optional[str]:
    """Write the flight-recorder dump: the last ``last_n`` buffered
    events + every state provider's snapshot + the tracing summary, as
    one JSON file. Returns the path, or None when the write failed
    (logged — a dump failure must never mask the original crash).

    Triggers wired in-tree: serving-engine loop crash,
    ``PoolExhaustedError`` escaping ``ServingEngine.step()``, and the
    fault-tolerance preemption handler's SIGTERM/SIGINT."""
    try:
        from .exporters import SINK_DIR_ENV, resolve_sink_path

        if path is None:
            name = (f"flight_{reason}_{os.getpid()}_"
                    f"{int(time.time() * 1000)}.json")
            if os.environ.get(SINK_DIR_ENV):
                path = resolve_sink_path(name)
            else:
                # never litter the cwd: unconfigured dumps go to tmp
                # (the warning log below carries the path)
                import tempfile

                path = os.path.join(tempfile.gettempdir(), name)
        else:
            path = resolve_sink_path(path)
        rec = {
            "reason": reason,
            "ts": time.time(),
            "pid": os.getpid(),
            "tracing": summary(),
            "events": events()[-int(last_n):],
            "state": state_snapshot(),
        }
        if extra:
            rec["extra"] = extra
        with open(path, "w") as fh:
            json.dump(rec, fh)
        _flight_dumps.labels(reason).inc()
        _last_dump_path[0] = path
        logger.warning("flight recorder dump (%s) -> %s", reason, path)
        return path
    except Exception:  # noqa: BLE001
        logger.exception("flight recorder dump failed (reason=%s)", reason)
        return None


# ---------------------------------------------------------------------------
# interop: profiler RecordEvent spans -> trace events
# ---------------------------------------------------------------------------


def _profiler_sink(name: str, t0_ns: int, t1_ns: int, event_type: int):
    _record("X", name, "profiler", current_trace(), threading.get_ident(),
            t0_ns, max(t1_ns - t0_ns, 0), None)


def attach_profiler_spans():
    """Forward every completed ``profiler.RecordEvent`` span into the
    trace buffer (cat=``profiler``), so one ``/trace`` export carries
    request lifecycle AND step-internal spans on a shared clock.
    Zero-cost when detached (the profiler checks one list index)."""
    from .. import profiler as _prof

    _prof._trace_sink[0] = _profiler_sink


def detach_profiler_spans():
    from .. import profiler as _prof

    _prof._trace_sink[0] = None


# recompile-monitor attribution: compile events land in the active trace
def _on_compile(entry: str, duration_s: float):
    if not tracing_enabled():
        return
    now = time.perf_counter_ns()
    dur = int(duration_s * 1e9)
    _record("X", f"xla_compile:{entry}", "compile", current_trace(),
            threading.get_ident(), now - dur, dur, {"entry": entry})
