// TCPStore: rendezvous key-value store for multi-host bootstrap.
//
// Native C++ equivalent of the reference's store
// (paddle/phi/core/distributed/store/tcp_store.h:121, tcp_utils.cc):
// a master-hosted KV with blocking get/wait and atomic add, used for
// rank rendezvous, barriers and checkpoint coordination. The TPU build
// keeps the same semantics but is transport-only — collective setup
// itself rides the PJRT coordination service.
//
// Wire protocol (little-endian, shared with the Python fallback client
// in paddle_tpu/distributed/store.py):
//   request : u8 cmd | u32 keylen | key bytes | payload
//   SET(1)  : payload = u32 vallen | bytes          -> reply u8 1
//   GET(2)  : payload = i64 timeout_ms              -> reply u32 len | bytes
//                                                      (len=0xFFFFFFFF on timeout)
//   ADD(3)  : payload = i64 delta                   -> reply i64 new value
//   WAIT(4) : payload = i64 timeout_ms              -> reply u8 (1 ok / 0 timeout)
//   CHECK(5): no payload                            -> reply u8 exists
//   DEL(6)  : no payload                            -> reply u8 existed
//   NKEYS(7): no payload (key ignored)              -> reply i64 count

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <climits>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

enum Cmd : uint8_t {
  kSet = 1,
  kGet = 2,
  kAdd = 3,
  kWait = 4,
  kCheck = 5,
  kDelete = 6,
  kNumKeys = 7,
};

constexpr uint32_t kTimeoutLen = 0xFFFFFFFFu;

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) {
      if (w < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

class Server {
 public:
  explicit Server(int port) : stop_(false), listen_fd_(-1), port_(0) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
        ::listen(listen_fd_, 128) != 0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return;
    }
    sockaddr_in got{};
    socklen_t len = sizeof(got);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&got), &len);
    port_ = ntohs(got.sin_port);
    accept_thread_ = std::thread([this] { AcceptLoop(); });
  }

  ~Server() { Stop(); }

  bool ok() const { return listen_fd_ >= 0; }
  int port() const { return port_; }

  void Stop() {
    bool expected = false;
    if (!stop_.compare_exchange_strong(expected, true)) return;
    if (listen_fd_ >= 0) ::shutdown(listen_fd_, SHUT_RDWR);
    {
      std::lock_guard<std::mutex> lk(mu_);
      for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
      cv_.notify_all();
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    for (auto& t : conn_threads_)
      if (t.joinable()) t.join();
    if (listen_fd_ >= 0) ::close(listen_fd_);
    listen_fd_ = -1;
  }

 private:
  void AcceptLoop() {
    while (!stop_.load()) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        if (stop_.load()) break;
        if (errno == EINTR) continue;
        break;
      }
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> lk(mu_);
      conn_fds_.push_back(fd);
      conn_threads_.emplace_back([this, fd] { Serve(fd); });
    }
  }

  void Serve(int fd) {
    while (!stop_.load()) {
      uint8_t cmd;
      if (!recv_all(fd, &cmd, 1)) break;
      uint32_t keylen;
      if (!recv_all(fd, &keylen, 4) || keylen > (64u << 20)) break;
      std::string key(keylen, '\0');
      if (keylen && !recv_all(fd, &key[0], keylen)) break;
      if (!Dispatch(fd, static_cast<Cmd>(cmd), key)) break;
    }
    ::close(fd);
  }

  bool Dispatch(int fd, Cmd cmd, const std::string& key) {
    switch (cmd) {
      case kSet: {
        uint32_t vallen;
        if (!recv_all(fd, &vallen, 4) || vallen > (256u << 20)) return false;
        std::string val(vallen, '\0');
        if (vallen && !recv_all(fd, &val[0], vallen)) return false;
        {
          std::lock_guard<std::mutex> lk(mu_);
          kv_[key] = std::move(val);
          cv_.notify_all();
        }
        uint8_t ok = 1;
        return send_all(fd, &ok, 1);
      }
      case kGet: {
        int64_t timeout_ms;
        if (!recv_all(fd, &timeout_ms, 8)) return false;
        std::string val;
        if (!WaitKey(key, timeout_ms, &val)) {
          uint32_t len = kTimeoutLen;
          return send_all(fd, &len, 4);
        }
        uint32_t len = static_cast<uint32_t>(val.size());
        return send_all(fd, &len, 4) && (val.empty() || send_all(fd, val.data(), val.size()));
      }
      case kAdd: {
        int64_t delta;
        if (!recv_all(fd, &delta, 8)) return false;
        int64_t result;
        {
          std::lock_guard<std::mutex> lk(mu_);
          int64_t cur = 0;
          auto it = kv_.find(key);
          if (it != kv_.end() && !it->second.empty()) cur = std::stoll(it->second);
          result = cur + delta;
          kv_[key] = std::to_string(result);
          cv_.notify_all();
        }
        return send_all(fd, &result, 8);
      }
      case kWait: {
        int64_t timeout_ms;
        if (!recv_all(fd, &timeout_ms, 8)) return false;
        uint8_t ok = WaitKey(key, timeout_ms, nullptr) ? 1 : 0;
        return send_all(fd, &ok, 1);
      }
      case kCheck: {
        std::lock_guard<std::mutex> lk(mu_);
        uint8_t ok = kv_.count(key) ? 1 : 0;
        return send_all(fd, &ok, 1);
      }
      case kDelete: {
        std::lock_guard<std::mutex> lk(mu_);
        uint8_t existed = kv_.erase(key) ? 1 : 0;
        return send_all(fd, &existed, 1);
      }
      case kNumKeys: {
        int64_t n;
        {
          std::lock_guard<std::mutex> lk(mu_);
          n = static_cast<int64_t>(kv_.size());
        }
        return send_all(fd, &n, 8);
      }
    }
    return false;
  }

  // Blocks until `key` exists (or timeout / shutdown). timeout_ms < 0 = forever.
  bool WaitKey(const std::string& key, int64_t timeout_ms, std::string* out) {
    std::unique_lock<std::mutex> lk(mu_);
    auto pred = [&] { return stop_.load() || kv_.count(key) > 0; };
    if (timeout_ms < 0) {
      cv_.wait(lk, pred);
    } else if (!cv_.wait_for(lk, std::chrono::milliseconds(timeout_ms), pred)) {
      return false;
    }
    auto it = kv_.find(key);
    if (it == kv_.end()) return false;
    if (out) *out = it->second;
    return true;
  }

  std::atomic<bool> stop_;
  int listen_fd_;
  int port_;
  std::thread accept_thread_;
  std::vector<std::thread> conn_threads_;
  std::vector<int> conn_fds_;
  std::unordered_map<std::string, std::string> kv_;
  std::mutex mu_;
  std::condition_variable cv_;
};

class Client {
 public:
  Client(const char* host, int port, long timeout_ms) : fd_(-1) {
    auto deadline =
        std::chrono::steady_clock::now() + std::chrono::milliseconds(timeout_ms);
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    // Retry until the server comes up (ranks race the master at startup).
    while (std::chrono::steady_clock::now() < deadline) {
      addrinfo* res = nullptr;
      if (::getaddrinfo(host, std::to_string(port).c_str(), &hints, &res) == 0) {
        int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
        if (fd >= 0 && ::connect(fd, res->ai_addr, res->ai_addrlen) == 0) {
          int one = 1;
          ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          fd_ = fd;
          ::freeaddrinfo(res);
          return;
        }
        if (fd >= 0) ::close(fd);
        ::freeaddrinfo(res);
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
    }
  }

  ~Client() {
    if (fd_ >= 0) ::close(fd_);
  }

  bool ok() const { return fd_ >= 0; }

  bool SendReq(Cmd cmd, const std::string& key, const void* payload, size_t plen) {
    std::lock_guard<std::mutex> lk(mu_);
    uint8_t c = cmd;
    uint32_t klen = static_cast<uint32_t>(key.size());
    return send_all(fd_, &c, 1) && send_all(fd_, &klen, 4) &&
           (key.empty() || send_all(fd_, key.data(), key.size())) &&
           (plen == 0 || send_all(fd_, payload, plen));
  }

  int fd() const { return fd_; }
  std::mutex& mu() { return mu_; }

 private:
  int fd_;
  std::mutex mu_;  // one request/response at a time per client
};

}  // namespace

extern "C" {

void* pts_server_start(int port) {
  auto* s = new Server(port);
  if (!s->ok()) {
    delete s;
    return nullptr;
  }
  return s;
}

int pts_server_port(void* h) { return h ? static_cast<Server*>(h)->port() : -1; }

void pts_server_stop(void* h) {
  if (!h) return;
  auto* s = static_cast<Server*>(h);
  s->Stop();
  delete s;
}

void* pts_client_new(const char* host, int port, long timeout_ms) {
  auto* c = new Client(host, port, timeout_ms);
  if (!c->ok()) {
    delete c;
    return nullptr;
  }
  return c;
}

void pts_client_free(void* h) { delete static_cast<Client*>(h); }

int pts_set(void* h, const char* key, const void* data, int len) {
  auto* c = static_cast<Client*>(h);
  std::string k(key);
  std::vector<char> payload(4 + (len > 0 ? len : 0));
  uint32_t vallen = static_cast<uint32_t>(len);
  std::memcpy(payload.data(), &vallen, 4);
  if (len > 0) std::memcpy(payload.data() + 4, data, len);
  if (!c->SendReq(kSet, k, payload.data(), payload.size())) return -1;
  uint8_t ok;
  std::lock_guard<std::mutex> lk(c->mu());
  return recv_all(c->fd(), &ok, 1) && ok == 1 ? 0 : -1;
}

// Returns 0 on success (caller frees *out with pts_buf_free), -1 timeout/error.
int pts_get(void* h, const char* key, long timeout_ms, void** out, int* outlen) {
  auto* c = static_cast<Client*>(h);
  int64_t t = timeout_ms;
  if (!c->SendReq(kGet, key, &t, 8)) return -1;
  std::lock_guard<std::mutex> lk(c->mu());
  uint32_t len;
  if (!recv_all(c->fd(), &len, 4)) return -1;
  if (len == kTimeoutLen) return -1;
  char* buf = static_cast<char*>(::malloc(len ? len : 1));
  if (len && !recv_all(c->fd(), buf, len)) {
    ::free(buf);
    return -1;
  }
  *out = buf;
  *outlen = static_cast<int>(len);
  return 0;
}

void pts_buf_free(void* p) { ::free(p); }

long long pts_add(void* h, const char* key, long long delta) {
  auto* c = static_cast<Client*>(h);
  int64_t d = delta;
  if (!c->SendReq(kAdd, key, &d, 8)) return LLONG_MIN;
  std::lock_guard<std::mutex> lk(c->mu());
  int64_t result;
  if (!recv_all(c->fd(), &result, 8)) return LLONG_MIN;
  return result;
}

int pts_wait(void* h, const char* key, long timeout_ms) {
  auto* c = static_cast<Client*>(h);
  int64_t t = timeout_ms;
  if (!c->SendReq(kWait, key, &t, 8)) return -1;
  std::lock_guard<std::mutex> lk(c->mu());
  uint8_t ok;
  if (!recv_all(c->fd(), &ok, 1)) return -1;
  return ok == 1 ? 0 : -1;
}

int pts_check(void* h, const char* key) {
  auto* c = static_cast<Client*>(h);
  if (!c->SendReq(kCheck, key, nullptr, 0)) return -1;
  std::lock_guard<std::mutex> lk(c->mu());
  uint8_t ok;
  if (!recv_all(c->fd(), &ok, 1)) return -1;
  return ok;
}

int pts_delete_key(void* h, const char* key) {
  auto* c = static_cast<Client*>(h);
  if (!c->SendReq(kDelete, key, nullptr, 0)) return -1;
  std::lock_guard<std::mutex> lk(c->mu());
  uint8_t existed;
  if (!recv_all(c->fd(), &existed, 1)) return -1;
  return existed;
}

long long pts_num_keys(void* h) {
  auto* c = static_cast<Client*>(h);
  if (!c->SendReq(kNumKeys, "", nullptr, 0)) return -1;
  std::lock_guard<std::mutex> lk(c->mu());
  int64_t n;
  if (!recv_all(c->fd(), &n, 8)) return -1;
  return n;
}

}  // extern "C"
