// Host tracer: low-overhead RecordEvent ring buffer.
//
// Native equivalent of the reference's HostTracer
// (paddle/fluid/platform/profiler/host_tracer.h:26, event instrumentation
// via RecordEvent event_tracing.h:43): host-side spans recorded from the
// dispatch layer / user code with ns timestamps + thread ids, drained by
// the Python profiler and merged with PJRT/XLA device traces into a
// chrome-trace export (chrometracing_logger.h:32 equivalent).
//
// Events live in a fixed ring (overwrite-oldest) guarded by a spinlock-ish
// mutex; Begin/End pair via returned slot ids so nesting is preserved.

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

constexpr int kNameLen = 64;

struct Event {
  char name[kNameLen];
  uint64_t tid;
  uint64_t start_ns;
  uint64_t end_ns;  // 0 while open
  uint32_t category;
  uint32_t consumed;  // drained already (not part of the exported payload)
};

static_assert(sizeof(Event) == kNameLen + 32, "Event layout is ABI");

uint64_t now_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

uint64_t this_tid() {
  return std::hash<std::thread::id>{}(std::this_thread::get_id());
}

class Tracer {
 public:
  explicit Tracer(size_t capacity) : events_(capacity), head_(0), base_(0),
                                     dropped_(0), enabled_(true) {}

  int64_t Begin(const char* name, uint32_t category) {
    if (!enabled_.load(std::memory_order_relaxed)) return -1;
    std::lock_guard<std::mutex> lk(mu_);
    size_t slot = head_ % events_.size();
    if (head_ - base_ >= events_.size()) dropped_++;
    Event& e = events_[slot];
    std::strncpy(e.name, name, kNameLen - 1);
    e.name[kNameLen - 1] = '\0';
    e.tid = this_tid();
    e.start_ns = now_ns();
    e.end_ns = 0;
    e.category = category;
    e.consumed = 0;
    return static_cast<int64_t>(head_++);
  }

  void End(int64_t id) {
    if (id < 0) return;
    std::lock_guard<std::mutex> lk(mu_);
    uint64_t uid = static_cast<uint64_t>(id);
    if (uid < base_) return;  // span drained before it ended (ids stay monotonic)
    if (head_ > events_.size() && uid < head_ - events_.size())
      return;  // slot already overwritten by ring wraparound
    events_[uid % events_.size()].end_ns = now_ns();
  }

  void Instant(const char* name, uint32_t category) {
    int64_t id = Begin(name, category);
    End(id);
  }

  // Copies completed, not-yet-consumed events (oldest first) into out.
  // Spans still open stay in the ring (they complete and drain later), so
  // base_ only advances past fully-consumed prefixes. head_ stays monotonic,
  // so outstanding Begin() ids never alias a post-drain slot.
  size_t Drain(Event* out, size_t max) {
    std::lock_guard<std::mutex> lk(mu_);
    size_t n = head_ - base_;
    if (n > events_.size()) n = events_.size();
    size_t start = head_ - n;
    size_t written = 0;
    for (size_t i = 0; i < n && written < max; ++i) {
      Event& e = events_[(start + i) % events_.size()];
      if (e.end_ns != 0 && !e.consumed) {
        out[written++] = e;
        e.consumed = 1;
      }
    }
    while (base_ < head_) {  // advance past the consumed prefix only
      Event& e = events_[base_ % events_.size()];
      if (head_ - base_ <= events_.size() && e.end_ns == 0) break;  // still open
      if (head_ - base_ <= events_.size() && !e.consumed) break;    // not copied (max hit)
      ++base_;
    }
    return written;
  }

  size_t Count() {
    std::lock_guard<std::mutex> lk(mu_);
    size_t n = head_ - base_;
    return n < events_.size() ? n : events_.size();
  }

  uint64_t Dropped() {
    std::lock_guard<std::mutex> lk(mu_);
    return dropped_;
  }

  void SetEnabled(bool on) { enabled_.store(on, std::memory_order_relaxed); }
  bool Enabled() { return enabled_.load(std::memory_order_relaxed); }

 private:
  std::vector<Event> events_;
  size_t head_;
  size_t base_;  // events below this index have been drained
  uint64_t dropped_;
  std::atomic<bool> enabled_;
  std::mutex mu_;
};

Tracer* g_tracer = nullptr;
std::mutex g_tracer_mu;

}  // namespace

extern "C" {

int pth_tracer_init(uint64_t capacity) {
  std::lock_guard<std::mutex> lk(g_tracer_mu);
  if (!g_tracer) g_tracer = new Tracer(capacity ? capacity : (1u << 16));
  return 0;
}

void pth_tracer_enable(int on) {
  if (g_tracer) g_tracer->SetEnabled(on != 0);
}

int pth_tracer_enabled() { return g_tracer && g_tracer->Enabled() ? 1 : 0; }

int64_t pth_record_begin(const char* name, uint32_t category) {
  return g_tracer ? g_tracer->Begin(name, category) : -1;
}

void pth_record_end(int64_t id) {
  if (g_tracer) g_tracer->End(id);
}

void pth_record_instant(const char* name, uint32_t category) {
  if (g_tracer) g_tracer->Instant(name, category);
}

uint64_t pth_tracer_count() { return g_tracer ? g_tracer->Count() : 0; }
uint64_t pth_tracer_dropped() { return g_tracer ? g_tracer->Dropped() : 0; }

// out must hold max * sizeof(Event) = max * 96 bytes.
uint64_t pth_tracer_drain(void* out, uint64_t max) {
  return g_tracer ? g_tracer->Drain(static_cast<Event*>(out), max) : 0;
}

}  // extern "C"
