// Dependency-graph job scheduler with a worker thread pool.
//
// Parity: the reference's async instruction executor —
// paddle/fluid/framework/new_executor/pir_interpreter.cc:1508
// (MultiThreadRunImpl over new_executor/workqueue/) and the fleet_executor
// Carrier/Interceptor graph (paddle/fluid/distributed/fleet_executor/).
//
// TPU role: orders host-side jobs (micro-batch stage launches, H2D feeds,
// checkpoint writes) respecting a dependency DAG. Each job invokes a
// caller-provided C callback (Python via ctypes CFUNCTYPE — callbacks that
// dispatch XLA executables release the GIL inside jax, so pool workers
// overlap device work with host scheduling).
//
// C ABI (ctypes-friendly, no C++ types across the boundary):
//   jsched_new(n_workers)                        -> handle
//   jsched_add_job(h, user_tag)                  -> job id (>=0)
//   jsched_add_dep(h, before_id, after_id)       -> 0/-1
//   jsched_run(h, cb, ctx)                       -> 0 ok, -1 error/cycle,
//        cb: void(*)(long job_id, long user_tag, void* ctx) called from
//        worker threads; jobs whose deps all completed run concurrently.
//   jsched_reset(h)  (keep graph, clear completion state for re-run)
//   jsched_free(h)

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace {

struct Job {
  int64_t tag;
  std::vector<int> deps;      // jobs this one waits for
  std::vector<int> dependents;
  int pending = 0;            // guarded by Scheduler::mu
};

struct Scheduler {
  int n_workers;
  std::vector<Job*> jobs;
  std::mutex mu;
  std::condition_variable cv;
  std::queue<int> ready;
  int remaining = 0;          // guarded by mu
  int running = 0;            // guarded by mu
  bool failed = false;        // guarded by mu; set on cycle detection

  explicit Scheduler(int workers) : n_workers(workers < 1 ? 1 : workers) {}
  ~Scheduler() {
    for (auto* j : jobs) delete j;
  }
};

using Callback = void (*)(int64_t, int64_t, void*);

void worker_loop(Scheduler* s, Callback cb, void* ctx) {
  for (;;) {
    int id;
    {
      std::unique_lock<std::mutex> lk(s->mu);
      s->cv.wait(lk, [&] {
        return !s->ready.empty() || s->remaining == 0 || s->failed ||
               (s->running == 0 && s->ready.empty());
      });
      if (s->failed || s->remaining == 0) {
        s->cv.notify_all();
        return;
      }
      if (s->ready.empty()) {
        // nothing runnable, nothing running, jobs remain: dependency cycle
        s->failed = true;
        s->cv.notify_all();
        return;
      }
      id = s->ready.front();
      s->ready.pop();
      s->running++;
    }
    cb(id, s->jobs[id]->tag, ctx);
    bool finished;
    {
      std::lock_guard<std::mutex> lk(s->mu);
      s->running--;
      s->remaining--;
      for (int d : s->jobs[id]->dependents) {
        if (--s->jobs[d]->pending == 0) s->ready.push(d);
      }
      finished = (s->remaining == 0);
      s->cv.notify_all();
    }
    if (finished) return;
  }
}

}  // namespace

extern "C" {

void* jsched_new(int n_workers) { return new Scheduler(n_workers); }

void jsched_free(void* h) { delete static_cast<Scheduler*>(h); }

int64_t jsched_add_job(void* h, int64_t tag) {
  auto* s = static_cast<Scheduler*>(h);
  auto* j = new Job();
  j->tag = tag;
  s->jobs.push_back(j);
  return static_cast<int64_t>(s->jobs.size()) - 1;
}

int jsched_add_dep(void* h, int64_t before, int64_t after) {
  auto* s = static_cast<Scheduler*>(h);
  if (before < 0 || after < 0 || before >= (int64_t)s->jobs.size() ||
      after >= (int64_t)s->jobs.size() || before == after)
    return -1;
  s->jobs[before]->dependents.push_back(static_cast<int>(after));
  s->jobs[after]->deps.push_back(static_cast<int>(before));
  return 0;
}

int jsched_run(void* h, Callback cb, void* ctx) {
  auto* s = static_cast<Scheduler*>(h);
  {
    std::lock_guard<std::mutex> lk(s->mu);
    while (!s->ready.empty()) s->ready.pop();
    s->failed = false;
    s->running = 0;
    s->remaining = static_cast<int>(s->jobs.size());
    for (size_t i = 0; i < s->jobs.size(); ++i) {
      s->jobs[i]->pending = static_cast<int>(s->jobs[i]->deps.size());
      if (s->jobs[i]->deps.empty()) s->ready.push(static_cast<int>(i));
    }
    if (s->jobs.empty()) return 0;
    if (s->ready.empty()) return -1;  // no roots: cycle
  }
  std::vector<std::thread> threads;
  int n = s->n_workers;
  for (int i = 0; i < n; ++i) threads.emplace_back(worker_loop, s, cb, ctx);
  {
    std::lock_guard<std::mutex> lk(s->mu);
    s->cv.notify_all();
  }
  for (auto& t : threads) t.join();
  std::lock_guard<std::mutex> lk(s->mu);
  return s->remaining == 0 ? 0 : -1;  // nonzero remaining: cycle/deadlock
}

int jsched_n_jobs(void* h) {
  return static_cast<int>(static_cast<Scheduler*>(h)->jobs.size());
}

}  // extern "C"
