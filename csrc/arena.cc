// Host staging arena: best-fit-with-coalescing allocator over one slab.
//
// Native equivalent of the reference's host allocator layer
// (paddle/phi/core/memory/allocation/auto_growth_best_fit_allocator.cc,
// buddy_allocator.cc, stats.h). On TPU there is no device allocator zoo —
// PJRT owns HBM — so the native allocator's job is host-side staging
// (checkpoint IO, batch collation, host transfers) with the reference's
// stats semantics (allocated / peak, memory/stats.h).
//
// Layout: every block has a 32-byte header {size, prev_size, free, magic}.
// Free blocks are kept in a size-ordered multimap (best-fit); physical
// neighbors coalesce on free via the prev_size back-link.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <new>

namespace {

constexpr uint64_t kMagic = 0xA110CA7EDB10C35Full;
constexpr size_t kAlign = 64;  // cache line; also good for vectorized memcpy

struct BlockHeader {
  uint64_t size;       // payload bytes (excluding header)
  uint64_t prev_size;  // payload bytes of the physically-previous block (0 = first)
  uint64_t free;
  uint64_t magic;
  uint64_t pad_[4];    // pad header to kAlign so payloads stay 64-aligned
};

static_assert(sizeof(BlockHeader) == kAlign,
              "header must equal kAlign so every payload is 64-byte aligned");

inline size_t align_up(size_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }

class Arena {
 public:
  explicit Arena(size_t capacity)
      : capacity_(align_up(capacity)), allocated_(0), peak_(0) {
    slab_ = static_cast<char*>(::aligned_alloc(kAlign, capacity_));
    if (!slab_) throw std::bad_alloc();
    auto* h = reinterpret_cast<BlockHeader*>(slab_);
    h->size = capacity_ - sizeof(BlockHeader);
    h->prev_size = 0;
    h->free = 1;
    h->magic = kMagic;
    free_blocks_.emplace(h->size, h);
  }

  ~Arena() { ::free(slab_); }

  void* Alloc(size_t nbytes) {
    if (nbytes == 0) nbytes = kAlign;
    nbytes = align_up(nbytes);
    std::lock_guard<std::mutex> lk(mu_);
    auto it = free_blocks_.lower_bound(nbytes);  // best fit
    if (it == free_blocks_.end()) return nullptr;
    BlockHeader* h = it->second;
    free_blocks_.erase(it);
    // split if the remainder can hold a header + one aligned unit
    if (h->size >= nbytes + sizeof(BlockHeader) + kAlign) {
      auto* rest = reinterpret_cast<BlockHeader*>(
          reinterpret_cast<char*>(h + 1) + nbytes);
      rest->size = h->size - nbytes - sizeof(BlockHeader);
      rest->prev_size = nbytes;
      rest->free = 1;
      rest->magic = kMagic;
      BlockHeader* after = Next(rest);
      if (after) after->prev_size = rest->size;
      h->size = nbytes;
      free_blocks_.emplace(rest->size, rest);
    }
    h->free = 0;
    allocated_ += h->size;
    if (allocated_ > peak_) peak_ = allocated_;
    return h + 1;
  }

  bool Free(void* p) {
    if (!p) return true;
    std::lock_guard<std::mutex> lk(mu_);
    auto* h = static_cast<BlockHeader*>(p) - 1;
    if (h->magic != kMagic || h->free) return false;
    allocated_ -= h->size;
    h->free = 1;
    // coalesce with next
    BlockHeader* nxt = Next(h);
    if (nxt && nxt->free) {
      EraseFree(nxt);
      h->size += sizeof(BlockHeader) + nxt->size;
      nxt->magic = 0;
    }
    // coalesce with prev
    if (h->prev_size != 0) {
      auto* prev = reinterpret_cast<BlockHeader*>(
          reinterpret_cast<char*>(h) - sizeof(BlockHeader) - h->prev_size);
      if (prev->free) {
        EraseFree(prev);
        prev->size += sizeof(BlockHeader) + h->size;
        h->magic = 0;
        h = prev;
      }
    }
    BlockHeader* after = Next(h);
    if (after) after->prev_size = h->size;
    free_blocks_.emplace(h->size, h);
    return true;
  }

  uint64_t allocated() const { return allocated_; }
  uint64_t peak() const { return peak_; }
  uint64_t capacity() const { return capacity_; }
  void reset_peak() {
    std::lock_guard<std::mutex> lk(mu_);
    peak_ = allocated_;
  }
  uint64_t largest_free() {
    std::lock_guard<std::mutex> lk(mu_);
    return free_blocks_.empty() ? 0 : free_blocks_.rbegin()->first;
  }

 private:
  BlockHeader* Next(BlockHeader* h) {
    char* end = reinterpret_cast<char*>(h + 1) + h->size;
    if (end >= slab_ + capacity_) return nullptr;
    return reinterpret_cast<BlockHeader*>(end);
  }

  void EraseFree(BlockHeader* h) {
    auto range = free_blocks_.equal_range(h->size);
    for (auto it = range.first; it != range.second; ++it) {
      if (it->second == h) {
        free_blocks_.erase(it);
        return;
      }
    }
  }

  char* slab_;
  size_t capacity_;
  uint64_t allocated_, peak_;
  std::multimap<uint64_t, BlockHeader*> free_blocks_;  // size -> block
  std::mutex mu_;
};

}  // namespace

extern "C" {

void* pta_create(uint64_t capacity) {
  try {
    return new Arena(capacity);
  } catch (...) {
    return nullptr;
  }
}

void pta_destroy(void* h) { delete static_cast<Arena*>(h); }

void* pta_alloc(void* h, uint64_t nbytes) {
  return static_cast<Arena*>(h)->Alloc(nbytes);
}

int pta_free(void* h, void* p) {
  return static_cast<Arena*>(h)->Free(p) ? 0 : -1;
}

uint64_t pta_allocated(void* h) { return static_cast<Arena*>(h)->allocated(); }
uint64_t pta_peak(void* h) { return static_cast<Arena*>(h)->peak(); }
uint64_t pta_capacity(void* h) { return static_cast<Arena*>(h)->capacity(); }
uint64_t pta_largest_free(void* h) { return static_cast<Arena*>(h)->largest_free(); }
void pta_reset_peak(void* h) { static_cast<Arena*>(h)->reset_peak(); }

}  // extern "C"
