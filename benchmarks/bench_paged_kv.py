"""Paged-KV serving lane: concurrent capacity + prefix-sharing A/B.

Two workloads against the SAME KV HBM budget:

1. **Long-tail capacity A/B** — the tentpole claim. A fixed budget of
   KV token-slots is spent two ways:

   - ``contiguous``: ``S_c`` slots * ``max_len`` tokens each (the
     pre-paging engine — capacity bounded by WORST-CASE length);
   - ``paged``: the same budget as a block pool
     (``S_c * max_len / block_size`` blocks) fronted by 4x the slots —
     capacity bounded by TOKENS IN FLIGHT, preemption-by-recompute
     keeps oversubscription safe.

   A long-tail request mix (mostly short, a few near-max_len) drains
   through both engines; the bench measures MEAN ACTIVE REQUESTS
   (concurrency actually sustained), wall time, and tok/s, and asserts
   per-request bit-parity with ``generation.generate`` plus the
   one-step-compile invariant while it runs. Acceptance:
   ``capacity_ratio >= 1.5``.

2. **Shared-prefix prefill savings** — 12 requests sharing a 64-token
   system prompt. After the first request populates the prefix cache,
   every follower adopts the shared blocks instead of recomputing them;
   the bench asserts the measured prefill-work saving is proportional
   to the shared fraction of the prompt (within 10%).

Artifact: ``benchmarks/bench_paged_kv.json``; ``tests/run_shards.py``
folds it into ``telemetry_lane.json`` as the ``paged_kv_bench`` block
(both lanes). CPU numbers size the structural win on the dev box; the
chip lane reruns this on TPU (where the paged flash-decode kernel is
compiled instead of interpreted/gathered).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import generation, serving
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import recompile

HERE = os.path.dirname(os.path.abspath(__file__))

MODEL_KW = dict(hidden_size=128, intermediate_size=256,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, vocab_size=1024,
                max_position_embeddings=256)

MAX_LEN = 128
BLOCK_SIZE = 16
CONTIG_SLOTS = 4                      # the HBM budget: 4 * 128 tokens
PAGED_SLOTS = 16                      # 4x the slots on the SAME budget
NUM_BLOCKS = CONTIG_SLOTS * MAX_LEN // BLOCK_SIZE + 1  # + dump block

# long-tail mix: (prompt_len, max_new_tokens) — 18 short, 6 long
LONG_TAIL = ([(6, 10), (9, 8), (14, 12), (7, 16), (11, 9), (5, 14)] * 3
             + [(48, 40), (64, 48), (40, 32), (56, 44), (60, 36), (44, 48)])

SYS_PROMPT_LEN = 64
SHARED_TAILS = 12
TAIL_LEN = 8


def make_requests(cfg, mix, seed):
    rng = np.random.RandomState(seed)
    return [(rng.randint(1, cfg.vocab_size, n).astype(np.int32),
             dict(max_new_tokens=m, do_sample=bool(i % 3 == 1),
                  top_k=8 if i % 3 == 1 else 0, seed=i))
            for i, (n, m) in enumerate(mix)]


def drain(engine, workload):
    reqs = [engine.submit(p, **params) for p, params in workload]
    t0 = time.perf_counter()
    engine.run_until_idle(max_steps=100_000)
    return reqs, time.perf_counter() - t0


def check_parity(model, reqs, workload):
    for req, (p, params) in zip(reqs, workload):
        ref = generation.generate(model, p[None], **params).numpy()[0, len(p):]
        got = np.asarray(req.result(timeout=1.0))
        if not (len(got) == len(ref) and np.array_equal(got, ref)):
            return False
    return True


def run_capacity_lane(model, cfg):
    workload = make_requests(cfg, LONG_TAIL, seed=7)
    gen_tokens = sum(params["max_new_tokens"] for _, params in workload)
    lanes = {}
    for mode, kwargs in (
            ("contiguous", dict(kv_mode="contiguous",
                                max_slots=CONTIG_SLOTS)),
            ("paged", dict(kv_mode="paged", max_slots=PAGED_SLOTS,
                           block_size=BLOCK_SIZE, num_blocks=NUM_BLOCKS,
                           prefix_caching=False))):
        eng = serving.ServingEngine(model, max_len=MAX_LEN,
                                    max_queue_depth=len(workload), **kwargs)
        drain(eng, workload)  # warmup: compile every executable
        base_steps, base_occ = eng._steps, eng._occupancy_integral
        step_before = recompile.entry_stats().get(
            "serving.step", {"compiles": 0, "retraces": 0})
        reqs, wall = drain(eng, workload)
        step_after = recompile.entry_stats().get(
            "serving.step", {"compiles": 0, "retraces": 0})
        steps = eng._steps - base_steps
        mean_active = (eng._occupancy_integral - base_occ) / max(1, steps)
        lanes[mode] = {
            "max_slots": eng.config.max_slots,
            "kv_token_budget": (NUM_BLOCKS - 1) * BLOCK_SIZE
            if mode == "paged" else CONTIG_SLOTS * MAX_LEN,
            "completed": sum(r.status == "completed" for r in reqs),
            "requests": len(workload),
            "mean_active_requests": round(mean_active, 2),
            "decode_steps": steps,
            "wall_s": round(wall, 3),
            "tok_s": round(gen_tokens / wall, 1),
            "parity": check_parity(model, reqs, workload),
            "step_compiles_measured":
                step_after["compiles"] - step_before["compiles"],
            "step_retraces_measured":
                step_after["retraces"] - step_before["retraces"],
        }
        if mode == "paged":
            lanes[mode]["num_blocks"] = NUM_BLOCKS - 1
            lanes[mode]["preemptions"] = eng._preempt_count
            lanes[mode]["kv_blocks_high_watermark"] = \
                eng.pool.stats()["high_watermark"]
    ratio = (lanes["paged"]["mean_active_requests"]
             / max(1e-9, lanes["contiguous"]["mean_active_requests"]))
    return {
        "kv_token_budget": CONTIG_SLOTS * MAX_LEN,
        "block_size": BLOCK_SIZE,
        "generated_tokens": gen_tokens,
        "contiguous": lanes["contiguous"],
        "paged": lanes["paged"],
        "capacity_ratio": round(ratio, 2),
        "tok_s_ratio": round(lanes["paged"]["tok_s"]
                             / max(1e-9, lanes["contiguous"]["tok_s"]), 2),
    }


def run_shared_prefix_lane(model, cfg):
    from paddle_tpu.serving import metrics as sm

    rng = np.random.RandomState(11)
    sys_prompt = rng.randint(1, cfg.vocab_size, SYS_PROMPT_LEN).astype(np.int32)
    prompts = [np.concatenate(
        [sys_prompt, rng.randint(1, cfg.vocab_size, TAIL_LEN).astype(np.int32)])
        for _ in range(SHARED_TAILS)]
    eng = serving.ServingEngine(model, max_slots=4, max_len=MAX_LEN,
                                block_size=BLOCK_SIZE, prefill_chunk=32,
                                max_queue_depth=SHARED_TAILS)
    computed0 = sm.tokens_total.labels("prompt").value()
    cached0 = sm.tokens_total.labels("prompt_cached").value()
    # the first request populates the prefix cache...
    first = eng.submit(prompts[0], max_new_tokens=8)
    eng.run_until_idle()
    # ...every follower adopts the shared system-prompt blocks
    reqs = [eng.submit(p, max_new_tokens=8) for p in prompts[1:]]
    eng.run_until_idle(max_steps=100_000)
    computed = sm.tokens_total.labels("prompt").value() - computed0
    cached = sm.tokens_total.labels("prompt_cached").value() - cached0
    parity = check_parity(
        model, [first] + reqs,
        [(p, dict(max_new_tokens=8)) for p in prompts])
    total_prompt = sum(len(p) for p in prompts)
    followers = SHARED_TAILS - 1
    # shareable per follower: the system prompt's FULL blocks
    shareable = (SYS_PROMPT_LEN // BLOCK_SIZE) * BLOCK_SIZE * followers
    savings = cached / max(1e-9, shareable)
    chunk = recompile.entry_stats().get("serving.prefill_chunk",
                                        {"compiles": 0, "retraces": 0})
    return {
        "requests": SHARED_TAILS,
        "system_prompt_tokens": SYS_PROMPT_LEN,
        "tail_tokens": TAIL_LEN,
        "prompt_tokens_total": total_prompt,
        "prompt_tokens_computed": int(computed),
        "prompt_tokens_cached": int(cached),
        "shared_fraction": round(SYS_PROMPT_LEN
                                 / (SYS_PROMPT_LEN + TAIL_LEN), 3),
        "savings_vs_shareable": round(savings, 3),
        "prefix_cache": eng.stats()["prefix_cache"],
        "cow_forks": eng.pool.stats()["cow_forks"],
        "parity": parity,
        "prefill_chunk_retraces": chunk["retraces"],
    }


def main():
    paddle.seed(0)
    cfg = LlamaConfig.tiny(**MODEL_KW)
    model = LlamaForCausalLM(cfg)

    capacity = run_capacity_lane(model, cfg)
    shared = run_shared_prefix_lane(model, cfg)

    verdicts = {
        "capacity_ge_1_5x": capacity["capacity_ratio"] >= 1.5,
        "prefix_savings_proportional": shared["savings_vs_shareable"] >= 0.9,
        "parity": (capacity["contiguous"]["parity"]
                   and capacity["paged"]["parity"] and shared["parity"]),
        "one_step_compile": (
            capacity["paged"]["step_compiles_measured"] == 0
            and capacity["paged"]["step_retraces_measured"] == 0),
    }
    result = {
        "bench": "paged_kv",
        "platform": jax.default_backend(),
        "model": {"family": "llama", **MODEL_KW},
        "capacity_ab": capacity,
        "shared_prefix": shared,
        "verdicts": verdicts,
    }
    path = os.path.join(HERE, "bench_paged_kv.json")
    with open(path, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps(result, indent=1))
    print(f"[bench_paged_kv] artifact -> {path}")
    ok = all(verdicts.values())
    if not ok:
        print("[bench_paged_kv] ACCEPTANCE FAILED", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
