"""Paged-KV serving lane: concurrent capacity + prefix-sharing A/B.

Two workloads against the SAME KV HBM budget:

1. **Long-tail capacity A/B** — the tentpole claim. A fixed budget of
   KV token-slots is spent two ways:

   - ``contiguous``: ``S_c`` slots * ``max_len`` tokens each (the
     pre-paging engine — capacity bounded by WORST-CASE length);
   - ``paged``: the same budget as a block pool
     (``S_c * max_len / block_size`` blocks) fronted by 4x the slots —
     capacity bounded by TOKENS IN FLIGHT, preemption-by-recompute
     keeps oversubscription safe.

   A long-tail request mix (mostly short, a few near-max_len) drains
   through both engines; the bench measures MEAN ACTIVE REQUESTS
   (concurrency actually sustained), wall time, and tok/s, and asserts
   per-request bit-parity with ``generation.generate`` plus the
   one-step-compile invariant while it runs. Acceptance:
   ``capacity_ratio >= 1.5``.

2. **Shared-prefix prefill savings** — 12 requests sharing a 64-token
   system prompt. After the first request populates the prefix cache,
   every follower adopts the shared blocks instead of recomputing them;
   the bench asserts the measured prefill-work saving is proportional
   to the shared fraction of the prompt (within 10%).

Artifact: ``benchmarks/bench_paged_kv.json``; ``tests/run_shards.py``
folds it into ``telemetry_lane.json`` as the ``paged_kv_bench`` block
(both lanes). CPU numbers size the structural win on the dev box; the
chip lane reruns this on TPU (where the paged flash-decode kernel is
compiled instead of interpreted/gathered).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import generation, serving
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import recompile

HERE = os.path.dirname(os.path.abspath(__file__))

MODEL_KW = dict(hidden_size=128, intermediate_size=256,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, vocab_size=1024,
                max_position_embeddings=256)

MAX_LEN = 128
BLOCK_SIZE = 16
CONTIG_SLOTS = 4                      # the HBM budget: 4 * 128 tokens
PAGED_SLOTS = 16                      # 4x the slots on the SAME budget
NUM_BLOCKS = CONTIG_SLOTS * MAX_LEN // BLOCK_SIZE + 1  # + dump block

# long-tail mix: (prompt_len, max_new_tokens) — 18 short, 6 long
LONG_TAIL = ([(6, 10), (9, 8), (14, 12), (7, 16), (11, 9), (5, 14)] * 3
             + [(48, 40), (64, 48), (40, 32), (56, 44), (60, 36), (44, 48)])

SYS_PROMPT_LEN = 64
SHARED_TAILS = 12
TAIL_LEN = 8

# ---- quantized-KV format lane (bf16 vs int8 vs fp8) ----------------------
# head_dim 64 — the serving geometry class. Capacity accounting is per
# CACHED TOKEN: bf16 stores 2 bytes/value, int8/fp8 store 1 byte/value
# + 4 bytes/head per token of f32 absmax scale, so the fixed-byte-budget
# multiplier is 2d / (d + 4) = 1.88x at d=64 (the scale tax shrinks as
# d grows; at d=16 it would only be 1.6x — head_dim matters).
FMT_MODEL_KW = dict(hidden_size=256, intermediate_size=256,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=2, vocab_size=1024,
                    max_position_embeddings=256)
FMT_MIX = ([(6, 8), (10, 6), (8, 10), (12, 8), (7, 6), (9, 8)]
           + [(40, 24), (48, 20), (36, 16), (44, 12)])
FMT_SLOTS = 12
# budget chosen so the POOL (not the slot count) binds concurrency on
# this mix: the bf16 lane runs pool-starved (preemption/queueing), the
# int8 lane's ~1.88x extra blocks convert directly into active requests
FMT_BF16_BLOCKS = 12          # the byte budget, expressed in bf16 blocks


def make_requests(cfg, mix, seed):
    rng = np.random.RandomState(seed)
    return [(rng.randint(1, cfg.vocab_size, n).astype(np.int32),
             dict(max_new_tokens=m, do_sample=bool(i % 3 == 1),
                  top_k=8 if i % 3 == 1 else 0, seed=i))
            for i, (n, m) in enumerate(mix)]


def drain(engine, workload):
    reqs = [engine.submit(p, **params) for p, params in workload]
    t0 = time.perf_counter()
    engine.run_until_idle(max_steps=100_000)
    return reqs, time.perf_counter() - t0


def check_parity(model, reqs, workload):
    for req, (p, params) in zip(reqs, workload):
        ref = generation.generate(model, p[None], **params).numpy()[0, len(p):]
        got = np.asarray(req.result(timeout=1.0))
        if not (len(got) == len(ref) and np.array_equal(got, ref)):
            return False
    return True


def run_capacity_lane(model, cfg):
    workload = make_requests(cfg, LONG_TAIL, seed=7)
    gen_tokens = sum(params["max_new_tokens"] for _, params in workload)
    lanes = {}
    for mode, kwargs in (
            ("contiguous", dict(kv_mode="contiguous",
                                max_slots=CONTIG_SLOTS)),
            ("paged", dict(kv_mode="paged", max_slots=PAGED_SLOTS,
                           block_size=BLOCK_SIZE, num_blocks=NUM_BLOCKS,
                           prefix_caching=False))):
        eng = serving.ServingEngine(model, max_len=MAX_LEN,
                                    max_queue_depth=len(workload), **kwargs)
        drain(eng, workload)  # warmup: compile every executable
        base_steps, base_occ = eng._steps, eng._occupancy_integral
        step_before = recompile.entry_stats().get(
            "serving.step", {"compiles": 0, "retraces": 0})
        reqs, wall = drain(eng, workload)
        step_after = recompile.entry_stats().get(
            "serving.step", {"compiles": 0, "retraces": 0})
        steps = eng._steps - base_steps
        mean_active = (eng._occupancy_integral - base_occ) / max(1, steps)
        lanes[mode] = {
            "max_slots": eng.config.max_slots,
            "kv_token_budget": (NUM_BLOCKS - 1) * BLOCK_SIZE
            if mode == "paged" else CONTIG_SLOTS * MAX_LEN,
            "completed": sum(r.status == "completed" for r in reqs),
            "requests": len(workload),
            "mean_active_requests": round(mean_active, 2),
            "decode_steps": steps,
            "wall_s": round(wall, 3),
            "tok_s": round(gen_tokens / wall, 1),
            "parity": check_parity(model, reqs, workload),
            "step_compiles_measured":
                step_after["compiles"] - step_before["compiles"],
            "step_retraces_measured":
                step_after["retraces"] - step_before["retraces"],
        }
        if mode == "paged":
            lanes[mode]["num_blocks"] = NUM_BLOCKS - 1
            lanes[mode]["preemptions"] = eng._preempt_count
            lanes[mode]["kv_blocks_high_watermark"] = \
                eng.pool.stats()["high_watermark"]
    ratio = (lanes["paged"]["mean_active_requests"]
             / max(1e-9, lanes["contiguous"]["mean_active_requests"]))
    return {
        "kv_token_budget": CONTIG_SLOTS * MAX_LEN,
        "block_size": BLOCK_SIZE,
        "generated_tokens": gen_tokens,
        "contiguous": lanes["contiguous"],
        "paged": lanes["paged"],
        "capacity_ratio": round(ratio, 2),
        "tok_s_ratio": round(lanes["paged"]["tok_s"]
                             / max(1e-9, lanes["contiguous"]["tok_s"]), 2),
    }


def run_shared_prefix_lane(model, cfg):
    from paddle_tpu.serving import metrics as sm

    rng = np.random.RandomState(11)
    sys_prompt = rng.randint(1, cfg.vocab_size, SYS_PROMPT_LEN).astype(np.int32)
    prompts = [np.concatenate(
        [sys_prompt, rng.randint(1, cfg.vocab_size, TAIL_LEN).astype(np.int32)])
        for _ in range(SHARED_TAILS)]
    eng = serving.ServingEngine(model, max_slots=4, max_len=MAX_LEN,
                                block_size=BLOCK_SIZE, prefill_chunk=32,
                                max_queue_depth=SHARED_TAILS)
    computed0 = sm.tokens_total.labels("prompt").value()
    cached0 = sm.tokens_total.labels("prompt_cached").value()
    # the first request populates the prefix cache...
    first = eng.submit(prompts[0], max_new_tokens=8)
    eng.run_until_idle()
    # ...every follower adopts the shared system-prompt blocks
    reqs = [eng.submit(p, max_new_tokens=8) for p in prompts[1:]]
    eng.run_until_idle(max_steps=100_000)
    computed = sm.tokens_total.labels("prompt").value() - computed0
    cached = sm.tokens_total.labels("prompt_cached").value() - cached0
    parity = check_parity(
        model, [first] + reqs,
        [(p, dict(max_new_tokens=8)) for p in prompts])
    total_prompt = sum(len(p) for p in prompts)
    followers = SHARED_TAILS - 1
    # shareable per follower: the system prompt's FULL blocks
    shareable = (SYS_PROMPT_LEN // BLOCK_SIZE) * BLOCK_SIZE * followers
    savings = cached / max(1e-9, shareable)
    chunk = recompile.entry_stats().get("serving.prefill_chunk",
                                        {"compiles": 0, "retraces": 0})
    return {
        "requests": SHARED_TAILS,
        "system_prompt_tokens": SYS_PROMPT_LEN,
        "tail_tokens": TAIL_LEN,
        "prompt_tokens_total": total_prompt,
        "prompt_tokens_computed": int(computed),
        "prompt_tokens_cached": int(cached),
        "shared_fraction": round(SYS_PROMPT_LEN
                                 / (SYS_PROMPT_LEN + TAIL_LEN), 3),
        "savings_vs_shareable": round(savings, 3),
        "prefix_cache": eng.stats()["prefix_cache"],
        "cow_forks": eng.pool.stats()["cow_forks"],
        "parity": parity,
        "prefill_chunk_retraces": chunk["retraces"],
    }


def _kernel_format_err(cfg, fmt):
    """Max-abs attention error of the quantized read path vs bf16-class
    float caches at the lane's geometry — the per-format numerics column
    (fast, kernel-level, no engine)."""
    import jax.numpy as jnp

    rng = np.random.RandomState(99)
    KV = cfg.num_key_value_heads
    d = cfg.hidden_size // cfg.num_attention_heads
    H = cfg.num_attention_heads
    q = jnp.asarray(rng.randn(4, 1, H, d), jnp.float32)
    kc = jnp.asarray(rng.randn(4, 128, KV, d), jnp.float32)
    vc = jnp.asarray(rng.randn(4, 128, KV, d), jnp.float32)
    pos = jnp.asarray([32, 64, 96, 127], jnp.int32)
    from paddle_tpu.generation import (dequantize_kv_buffer,
                                       kv_cache_write_quant,
                                       make_kv_caches)
    from paddle_tpu.nn import functional as F

    def _attend(k, v):
        # the XLA grouped fallback — format-independent oracle
        import paddle_tpu as pt

        kpos = np.arange(128)
        m = (kpos[None, None] <= np.asarray(pos)[:, None, None])
        mask = jnp.asarray(np.where(m[:, None], 0.0, -1e30), jnp.float32)
        return F.grouped_query_sdpa(
            pt.to_tensor(q), pt.to_tensor(k), pt.to_tensor(v),
            attn_mask=pt.to_tensor(mask)).numpy()

    ref = _attend(kc, vc)
    caches = make_kv_caches(cfg, 4, 128, jnp.float32, fmt)
    ck, cks = kv_cache_write_quant(caches[0]["k"], caches[0]["ks"], kc, 0,
                                   fmt)
    cv, cvs = kv_cache_write_quant(caches[0]["v"], caches[0]["vs"], vc, 0,
                                   fmt)
    kd = dequantize_kv_buffer(ck, cks, jnp.float32)._data
    vd = dequantize_kv_buffer(cv, cvs, jnp.float32)._data
    got = _attend(kd, vd)
    return float(np.abs(got - ref).max())


def run_format_lane():
    """bf16 vs int8 (vs fp8) at ONE fixed KV byte budget: the pool each
    format affords (host-side accounting — bytes per cached token incl.
    scale overhead, at the canonical bf16 compute dtype), the measured
    concurrency/throughput of a long-tail drain through that pool, and
    the per-format numerics error. Acceptance: int8 holds >= 1.8x the
    tokens (and therefore concurrent requests at a token-bound mix) of
    bf16 on the same bytes."""
    import jax.numpy as jnp

    from paddle_tpu.generation import kv_cache_bytes_per_token
    from paddle_tpu.quantization import intx

    paddle.seed(3)
    cfg = LlamaConfig.tiny(**FMT_MODEL_KW)
    model = LlamaForCausalLM(cfg)
    workload = make_requests(cfg, FMT_MIX, seed=23)
    gen_tokens = sum(params["max_new_tokens"] for _, params in workload)

    bpt_bf16 = kv_cache_bytes_per_token(cfg, "bf16", jnp.bfloat16)
    budget_bytes = FMT_BF16_BLOCKS * BLOCK_SIZE * bpt_bf16
    formats = ["bf16", "int8"] + (["fp8"] if intx.fp8_available() else [])
    lanes = {}
    for fmt in formats:
        bpt = (bpt_bf16 if fmt == "bf16"
               else kv_cache_bytes_per_token(cfg, fmt))
        blocks = int(budget_bytes // (bpt * BLOCK_SIZE))
        eng = serving.ServingEngine(
            model, max_slots=FMT_SLOTS, max_len=128,
            block_size=BLOCK_SIZE, num_blocks=blocks + 1,
            prefix_caching=False, kv_format=fmt,
            max_queue_depth=len(workload))
        drain(eng, workload)  # warmup: compile every executable
        base_steps, base_occ = eng._steps, eng._occupancy_integral
        reqs, wall = drain(eng, workload)
        steps = eng._steps - base_steps
        mean_active = (eng._occupancy_integral - base_occ) / max(1, steps)
        # parity spot-check on 4 requests vs generate at the SAME format
        parity = True
        for req, (p, params) in list(zip(reqs, workload))[:4]:
            ref = generation.generate(
                model, p[None], kv_format=fmt,
                **params).numpy()[0, len(p):]
            got = np.asarray(req.result(timeout=5.0))
            parity = parity and np.array_equal(got, ref)
        lanes[fmt] = {
            "bytes_per_token": bpt,
            "blocks_at_budget": blocks,
            "capacity_tokens_at_budget": blocks * BLOCK_SIZE,
            "completed": sum(r.status == "completed" for r in reqs),
            "mean_active_requests": round(mean_active, 2),
            "wall_s": round(wall, 3),
            "tok_s": round(gen_tokens / wall, 1),
            "preemptions": eng._preempt_count,
            "parity": parity,
            "max_abs_err_vs_bf16": (
                0.0 if fmt == "bf16" else
                round(_kernel_format_err(cfg, fmt), 5)),
        }
    for fmt in formats[1:]:
        lanes[fmt]["capacity_vs_bf16"] = round(
            lanes[fmt]["capacity_tokens_at_budget"]
            / lanes["bf16"]["capacity_tokens_at_budget"], 3)
        lanes[fmt]["mean_active_vs_bf16"] = round(
            lanes[fmt]["mean_active_requests"]
            / max(1e-9, lanes["bf16"]["mean_active_requests"]), 2)
        lanes[fmt]["tok_s_vs_bf16"] = round(
            lanes[fmt]["tok_s"] / max(1e-9, lanes["bf16"]["tok_s"]), 2)
    return {
        "model": {"family": "llama", **FMT_MODEL_KW},
        "head_dim": cfg.hidden_size // cfg.num_attention_heads,
        "kv_byte_budget": budget_bytes,
        "block_size": BLOCK_SIZE,
        "slots": FMT_SLOTS,
        "requests": len(workload),
        "formats": lanes,
    }


def main():
    paddle.seed(0)
    cfg = LlamaConfig.tiny(**MODEL_KW)
    model = LlamaForCausalLM(cfg)

    capacity = run_capacity_lane(model, cfg)
    shared = run_shared_prefix_lane(model, cfg)
    formats = run_format_lane()

    verdicts = {
        "capacity_ge_1_5x": capacity["capacity_ratio"] >= 1.5,
        "prefix_savings_proportional": shared["savings_vs_shareable"] >= 0.9,
        "parity": (capacity["contiguous"]["parity"]
                   and capacity["paged"]["parity"] and shared["parity"]),
        "one_step_compile": (
            capacity["paged"]["step_compiles_measured"] == 0
            and capacity["paged"]["step_retraces_measured"] == 0),
        # the quantized-KV acceptance: int8 >= 1.8x tokens (and thus
        # token-bound concurrency) at a FIXED byte budget, with every
        # format's engine bit-matching generate at the same format
        "int8_capacity_ge_1_8x":
            formats["formats"]["int8"]["capacity_vs_bf16"] >= 1.8,
        "format_parity": all(l["parity"]
                             for l in formats["formats"].values()),
    }
    result = {
        "bench": "paged_kv",
        "platform": jax.default_backend(),
        "model": {"family": "llama", **MODEL_KW},
        "capacity_ab": capacity,
        "shared_prefix": shared,
        "kv_format_ab": formats,
        "verdicts": verdicts,
    }
    path = os.path.join(HERE, "bench_paged_kv.json")
    with open(path, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps(result, indent=1))
    print(f"[bench_paged_kv] artifact -> {path}")
    ok = all(verdicts.values())
    if not ok:
        print("[bench_paged_kv] ACCEPTANCE FAILED", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
