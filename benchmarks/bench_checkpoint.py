"""Checkpoint lane: train-thread pause of async vs synchronous saves.

The acceptance number for the fault-tolerance layer: an async
checkpoint's TRAIN-THREAD cost (device->host snapshot + bounded-queue
enqueue — what ``AsyncCheckpointer.save`` does before returning) must be
< 10% of a full synchronous ``save_train_state`` (serialize + fsync +
digest + atomic rename) for the same state.

Methodology: a synthetic model+optimizer state dict of ``--mb``
megabytes (default 64 — a few transformer blocks' worth; the ratio only
improves with size because the sync path's pickle+fsync+sha256 scale
with bytes while the snapshot is one device_get). Each mode runs one
warmup then ``--reps`` measured saves to distinct step dirs; the async
pause is measured at ``save()`` return, with ``wait_until_finished``
AFTER the clock stops (the background commit is the part training
doesn't wait for). Min-of-reps is reported (noise floor), mean quoted.

Artifact: ``benchmarks/bench_checkpoint.json`` — per-mode timings, the
pause ratio, and the pass/fail verdict; ``tests/run_shards.py`` folds it
into ``telemetry_lane.json`` as ``checkpoint_bench``.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.fault_tolerance import AsyncCheckpointer, save_train_state
from paddle_tpu.observability import metrics as _m

HERE = os.path.dirname(os.path.abspath(__file__))


def make_state(mb: int) -> dict:
    """A training-shaped state dict: params + 2x Adam moments, totalling
    ~``mb`` MB of float32."""
    total = mb * (1 << 20) // 4  # f32 elements
    n_param = total // 3
    rs = np.random.RandomState(0)
    width = 1024
    rows = max(1, n_param // width)
    w = paddle.to_tensor(rs.randn(rows, width).astype(np.float32))
    m1 = paddle.to_tensor(np.zeros((rows, width), np.float32))
    m2 = paddle.to_tensor(np.ones((rows, width), np.float32))
    return {"model": {"w": w},
            "optimizer": {"w_moment1": m1, "w_moment2": m2, "@step": 123}}


def bench_sync(state, root, reps):
    times = []
    for i in range(reps + 1):  # +1 warmup
        t0 = time.perf_counter()
        save_train_state(os.path.join(root, f"sync_step_{i:08d}"), state,
                         meta={"global_step": i},
                         extra_marker={"step": i})
        dt = time.perf_counter() - t0
        if i:
            times.append(dt)
    return times


def bench_async(state, root, reps, paced: bool):
    """``paced=True`` models the real cadence (a save every N train
    steps, disk keeps up): drain between saves, so the measured pause is
    the pure snapshot+enqueue. ``paced=False`` hammers saves
    back-to-back into the bounded queue — the backpressure regime, where
    save() deliberately blocks rather than buffering snapshots."""
    ck = AsyncCheckpointer(root, queue_size=2)
    pauses = []
    for i in range(reps + 1):
        t0 = time.perf_counter()
        ck.save(i, state, meta={"global_step": i})
        dt = time.perf_counter() - t0  # train thread is free again HERE
        if i:
            pauses.append(dt)
        if paced:
            ck.wait_until_finished()
    ck.wait_until_finished()
    ck.close()
    return pauses


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=int, default=64,
                    help="state-dict size in MB")
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--out", default=os.path.join(HERE,
                                                  "bench_checkpoint.json"))
    args = ap.parse_args(argv)

    state = make_state(args.mb)
    workdir = tempfile.mkdtemp(prefix="paddle_tpu_bench_ckpt_")
    try:
        sync_s = bench_sync(state, os.path.join(workdir, "sync"), args.reps)
        async_s = bench_async(state, os.path.join(workdir, "paced"),
                              args.reps, paced=True)
        burst_s = bench_async(state, os.path.join(workdir, "burst"),
                              args.reps, paced=False)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    sync_min, async_min = min(sync_s), min(async_s)
    ratio = async_min / sync_min
    # observability cross-check: the snapshot histogram saw these pauses
    snap_hist = _m.get_registry().get("paddle_tpu_checkpoint_snapshot_seconds")
    snap_sum = snap_hist.value() if snap_hist is not None else None

    result = {
        "platform": paddle.get_device(),
        "state_mb": args.mb,
        "reps": args.reps,
        "sync_save_s": {"min": round(sync_min, 4),
                        "mean": round(float(np.mean(sync_s)), 4),
                        "all": [round(t, 4) for t in sync_s]},
        "async_train_thread_pause_s": {
            "min": round(async_min, 4),
            "mean": round(float(np.mean(async_s)), 4),
            "all": [round(t, 4) for t in async_s]},
        "async_backpressure_pause_s": {
            # back-to-back saves into the bounded (size-2) queue: once it
            # fills, save() blocks ~one commit — by design, so snapshots
            # never pile up in host RAM
            "min": round(min(burst_s), 4),
            "mean": round(float(np.mean(burst_s)), 4),
            "all": [round(t, 4) for t in burst_s]},
        "pause_ratio_async_vs_sync": round(ratio, 4),
        "target_ratio": 0.10,
        "verdict": "PASS" if ratio < 0.10 else "FAIL",
        "snapshot_seconds_histogram_sum": (round(snap_sum, 4)
                                           if snap_sum is not None else None),
    }
    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result, indent=1))
    print(f"\nasync train-thread pause {async_min * 1e3:.1f} ms vs sync save "
          f"{sync_min * 1e3:.1f} ms -> ratio {ratio:.3f} "
          f"({result['verdict']}, target < 0.10)")
    return 0 if ratio < 0.10 else 1


if __name__ == "__main__":
    sys.exit(main())
