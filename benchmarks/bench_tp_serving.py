"""Tensor-parallel serving lane: tp=1 vs tp=2/tp=4 — throughput,
per-chip HBM residency, and bit-parity verdicts.

One deterministic mixed greedy/sampled workload through three engines
built from the SAME model at ``tp=1``, ``tp=2``, ``tp=4`` (the host
mesh: 8 virtual XLA:CPU devices on the dev box, a real slice on chip):

- ``tok_s``: wall-clock decode throughput per lane, best-of-3 passes
  over a warmed engine. On CPU the collectives are memcpy-priced, so
  tp>1 runs near (or below) tp=1 — the pinned number is a regression
  fence for the sharded executables' dispatch overhead, not a speedup
  claim; the chip lane measures the real scaling.
- ``per-chip HBM``: weight and KV-pool bytes per device from the HBM
  ledger (weights report their exact sharded residency via
  ``Array.sharding.shard_shape``; KV pools divide by tp on the kv-heads
  axis). The verdict pins the POINT of TP — per-chip weight residency
  at tp=2 must be under 60% of the tp=1 footprint (Megatron shards the
  matmul weights; norms/rope tables replicate).
- ``parity``: every tp=2/tp=4 token stream must be bit-identical to
  its tp=1 twin (greedy AND sampled) — failure flips the exit code.
- ``zero retraces`` across the passes, and warmup() covering the first
  request's compiles, same bars as the router/spec lanes.

Artifact: ``benchmarks/bench_tp.json``; ``tests/run_shards.py`` folds
it into ``telemetry_lane.json`` as ``tp_bench`` and the perf gate reads
``tp.tp2_tok_s`` / ``tp.parity`` / ``tp.weight_hbm_frac_tp2`` from it
(pinned in ``perf_baseline.json``).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import serving
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import perf, recompile

HERE = os.path.dirname(os.path.abspath(__file__))

# (prompt_len, params) — mixed lengths + greedy/sampled, enough tokens
# that decode dominates the wall clock
WORKLOAD = [
    (5, dict(max_new_tokens=40)),
    (9, dict(max_new_tokens=32, do_sample=True, temperature=0.8,
             top_k=8, seed=1)),
    (14, dict(max_new_tokens=48)),
    (26, dict(max_new_tokens=24, do_sample=True, top_p=0.9, seed=2)),
    (7, dict(max_new_tokens=40)),
    (11, dict(max_new_tokens=24, do_sample=True, temperature=1.1,
              top_k=12, seed=3)),
    (19, dict(max_new_tokens=32)),
    (30, dict(max_new_tokens=40, do_sample=True, top_k=64, top_p=0.95,
              seed=4)),
]
MAX_SLOTS = 4
MAX_LEN = 96
TP_DEGREES = (1, 2, 4)
PASSES = 3

# weight-streaming-bound decode; kv heads divide by 4 so tp=4 shards
# the pools (same sizing as bench_router's model)
MODEL_KW = dict(hidden_size=256, intermediate_size=512,
                num_hidden_layers=3, num_attention_heads=8,
                num_key_value_heads=4, vocab_size=2048)


def make_workload(cfg):
    rng = np.random.RandomState(42)
    return [(rng.randint(1, cfg.vocab_size, n).astype(np.int32), p)
            for n, p in WORKLOAD]


def serving_retraces():
    return sum(v["retraces"] for k, v in recompile.entry_stats().items()
               if k.startswith("serving."))


def hbm_components():
    comps = perf.hbm_ledger()["components"]
    out = {}
    for name in ("serving_model_weights", "serving_kv_pool"):
        c = comps.get(name) or {}
        out[name] = {"bytes": c.get("bytes"),
                     "bytes_per_device": c.get("bytes_per_device",
                                               c.get("bytes"))}
    return out


def run_lane(model, workload, tp):
    eng = serving.ServingEngine(model, max_slots=MAX_SLOTS,
                                max_len=MAX_LEN, tp=tp)
    winfo = eng.warmup()
    retr0 = serving_retraces()
    compiles0 = recompile.total_compiles()

    outputs = None
    best_tok_s = 0.0
    for _ in range(PASSES):
        t0 = time.perf_counter()
        reqs = [eng.submit(p, params=serving.SamplingParams(**params))
                for p, params in workload]
        eng.run_until_idle(max_steps=50000)
        wall = time.perf_counter() - t0
        outs = [np.asarray(r.result(timeout=5.0)) for r in reqs]
        if outputs is None:
            outputs = outs
        tokens = sum(len(o) for o in outs)
        best_tok_s = max(best_tok_s, tokens / wall)

    hbm = hbm_components()
    lane = {
        "tp": tp,
        "tok_s": round(best_tok_s, 1),
        "warmup_compiles": winfo["compiles"],
        "warmup_wall_s": winfo["wall_s"],
        "post_warmup_compiles": recompile.total_compiles() - compiles0,
        "new_retraces": serving_retraces() - retr0,
        "weight_bytes": hbm["serving_model_weights"]["bytes"],
        "weight_bytes_per_device":
            hbm["serving_model_weights"]["bytes_per_device"],
        "kv_bytes": hbm["serving_kv_pool"]["bytes"],
        "kv_bytes_per_device": hbm["serving_kv_pool"]["bytes_per_device"],
    }
    return lane, outputs


def main():
    paddle.seed(0)
    cfg = LlamaConfig(**MODEL_KW)
    model = LlamaForCausalLM(cfg)
    workload = make_workload(cfg)
    print(f"[bench_tp] model {MODEL_KW['hidden_size']}h x "
          f"{MODEL_KW['num_hidden_layers']}L, {len(workload)} requests, "
          f"tp degrees {TP_DEGREES}", flush=True)

    lanes, outputs = {}, {}
    for tp in TP_DEGREES:
        lane, outs = run_lane(model, workload, tp)
        lanes[f"tp{tp}"], outputs[tp] = lane, outs
        print(f"[bench_tp] tp={tp}: {lane['tok_s']} tok/s, "
              f"weights/chip {lane['weight_bytes_per_device']}B, "
              f"kv/chip {lane['kv_bytes_per_device']}B, warmup "
              f"{lane['warmup_compiles']} compiles "
              f"({lane['warmup_wall_s']}s)", flush=True)

    parity = {
        f"tp{tp}": all(np.array_equal(a, b)
                       for a, b in zip(outputs[1], outputs[tp]))
        for tp in TP_DEGREES if tp != 1}
    w1 = lanes["tp1"]["weight_bytes"]
    for tp in TP_DEGREES[1:]:
        lanes[f"tp{tp}"]["weight_bytes_per_device_frac"] = round(
            lanes[f"tp{tp}"]["weight_bytes_per_device"] / w1, 4)

    verdicts = {
        "parity_bitwise": all(parity.values()),
        # the POINT of TP: per-chip weight residency shrinks (matmul
        # weights shard 1/tp; norms/rope replicate)
        "tp2_weight_frac_lt_0p6":
            lanes["tp2"]["weight_bytes_per_device_frac"] < 0.6,
        "tp4_weight_frac_lt_0p35":
            lanes["tp4"]["weight_bytes_per_device_frac"] < 0.35,
        "kv_divides_by_tp": all(
            lanes[f"tp{tp}"]["kv_bytes_per_device"]
            == lanes[f"tp{tp}"]["kv_bytes"] // tp
            for tp in TP_DEGREES[1:]),
        "zero_retraces": all(l["new_retraces"] == 0
                             for l in lanes.values()),
        "warmup_covers_first_request": all(
            l["post_warmup_compiles"] == 0 for l in lanes.values()),
    }
    print(f"[bench_tp] parity {parity}, verdicts "
          f"{ {k: v for k, v in verdicts.items() if not v} or 'all pass' }",
          flush=True)

    out = {
        "model": MODEL_KW,
        "workload_requests": len(workload),
        "max_slots": MAX_SLOTS,
        "passes": PASSES,
        "lanes": lanes,
        "parity": {k: float(v) for k, v in parity.items()},
        "parity_all": float(all(parity.values())),
        "verdicts": verdicts,
    }
    path = os.path.join(HERE, "bench_tp.json")
    with open(path, "w") as fh:
        json.dump(out, fh, indent=1)
    print(f"[bench_tp] -> {path}", flush=True)
    failed = [k for k, v in verdicts.items() if not v]
    if failed:
        print(f"[bench_tp] VERDICTS FAILED: {failed}", flush=True)
        return 1
    print("[bench_tp] all verdicts passed", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
