"""Weight-only quantized matmul A/B: Pallas dequant-fused kernel vs the
XLA dequant-fusion fallback vs the float matmul.

The decode-hot Linear shapes of a served model (qkv/o projection, MLP,
lm_head at small decode batch) timed three ways per format:

- ``float``:  ``x @ w`` with full-precision weights (the HBM baseline);
- ``xla``:    ``nn.quant.weight_only_linear``'s fallback — int8/fp8
              convert+scale fused into the matmul's weight read;
- ``kernel``: ``pallas_kernels.quant_matmul`` — dequant in the Pallas
              weight-load prologue, per-channel scale on the f32
              accumulator.

Parity (kernel vs xla, same quantized weights) is asserted per shape.
On CPU the kernel runs in the Pallas INTERPRETER: timings are recorded
for the curious, only parity gates the lane. On TPU the interesting
number is kernel-vs-float at the weight-bound shapes (the ~2x weight
byte cut), plus kernel-vs-xla (is the structural fusion beating the
barrier-pinned XLA form?).

Artifact: ``benchmarks/bench_quant.json``; ``tests/run_shards.py`` folds
it into ``telemetry_lane.json`` as the ``quant_bench`` block.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.nn.quant import weight_quantize
from paddle_tpu.pallas_kernels.quant_matmul import quant_matmul
from paddle_tpu.quantization import intx

HERE = os.path.dirname(os.path.abspath(__file__))
ON_TPU = jax.default_backend() == "tpu"

# (label, m, k, n): decode-batch activations against serving weights
SHAPES = ([("qkv_proj", 8, 2048, 2048), ("mlp_up", 8, 2048, 8192),
           ("lm_head", 8, 2048, 32000)] if ON_TPU else
          [("qkv_proj", 4, 256, 256), ("mlp_up", 4, 256, 512),
           ("lm_head", 4, 256, 1024)])

FORMATS = ["int8"] + (["fp8"] if intx.fp8_available() else [])


def _time(fn, *args, iters=20, warmup=3):
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best * 1e3


def run_shape(label, m, k, n, fmt, dtype):
    rng = np.random.RandomState(hash((label, fmt)) % (2 ** 31))
    import paddle_tpu as paddle

    x = jnp.asarray(rng.randn(m, k) * 0.1, dtype)
    w = jnp.asarray(rng.randn(k, n) * 0.05, jnp.float32)
    q, s = weight_quantize(paddle.to_tensor(w), algo=f"weight_only_{fmt}")
    qa, sa = q._data, s._data
    wd = w.astype(dtype)

    flt = jax.jit(lambda x, w: (x @ w).astype(x.dtype))
    xla = jax.jit(lambda x, q, s: (
        x @ (jax.lax.optimization_barrier(q).astype(x.dtype)
             * s[:, None].astype(x.dtype)).T))
    kern = jax.jit(lambda x, q, s: quant_matmul(x, q, s))

    out_x = np.asarray(xla(x, qa, sa), np.float32)
    out_k = np.asarray(kern(x, qa, sa), np.float32)
    out_f = np.asarray(flt(x, wd), np.float32)
    denom = max(np.abs(out_f).max(), 1e-9)
    err_vs_float = float(np.abs(out_k - out_f).max() / denom)
    kernel_vs_xla_err = float(np.abs(out_k - out_x).max() / denom)

    float_ms = _time(flt, x, wd)
    xla_ms = _time(xla, x, qa, sa)
    kernel_ms = _time(kern, x, qa, sa)
    tol = 5e-3 if dtype == jnp.float32 else 5e-2
    return {
        "shape": label, "m": m, "k": k, "n": n, "fmt": fmt,
        "float_ms": round(float_ms, 4),
        "xla_dequant_ms": round(xla_ms, 4),
        "kernel_ms": round(kernel_ms, 4),
        "kernel_vs_float": round(float_ms / kernel_ms, 2),
        "kernel_vs_xla": round(xla_ms / kernel_ms, 2),
        "rel_err_vs_float": err_vs_float,
        "kernel_vs_xla_rel_err": kernel_vs_xla_err,
        "parity": bool(kernel_vs_xla_err < tol),
    }


def main():
    dtype = jnp.bfloat16 if ON_TPU else jnp.float32
    rows = [run_shape(*sh, fmt, dtype) for sh in SHAPES for fmt in FORMATS]
    parity_ok = all(r["parity"] for r in rows)
    result = {
        "bench": "quant_matmul",
        "platform": jax.default_backend(),
        "dtype": str(jnp.dtype(dtype)),
        "formats": FORMATS,
        "configs": rows,
        "parity": parity_ok,
        # CPU: interpreter timings — parity-only lane; the weight-byte
        # win is a chip statement (see README capacity math)
        "mode": "compiled" if ON_TPU else "interpret (parity only)",
    }
    path = os.path.join(HERE, "bench_quant.json")
    with open(path, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps(result, indent=1))
    print(f"[bench_quant_matmul] artifact -> {path}")
    if not parity_ok:
        print("[bench_quant_matmul] ACCEPTANCE FAILED", file=sys.stderr)
    return 0 if parity_ok else 1


if __name__ == "__main__":
    sys.exit(main())
