"""Self-healing + overload-control lane: supervisor tax on the normal
path, DAGOR-style priority shedding under 2x oversubscription, and the
poison-request crash-loop bill.

Three lanes, deterministic workloads:

- ``overhead``: the same staggered workload through a bare
  ``ServingEngine`` vs an ``EngineSupervisor`` wrapping an identical
  engine — best-of-3 alternating passes. The supervisor's normal-path
  cost is one fingerprint hash + one lock hop per submit and a crash
  hook that never fires, so the acceptance bar is <2% throughput loss;
  the measured number is pinned in ``perf_baseline.json``
  (``overload.supervisor_overhead_pct``, direction lower).
- ``overload``: one engine, oversubscribed. An interactive stream
  (staggered, deadlined) rides alongside a CLOSED-LOOP batch flood — a
  hammering submitter that keeps the admission queue full for the
  whole window, whatever the host's decode speed. Three passes:
  UNCONTENDED (interactive alone — the goodput baseline), UNCONTROLLED
  (the flood submitted at the same priority class: interactive
  arrivals bounce off the full FCFS queue and the survivors' TTFT tail
  stretches), CONTROLLED (the flood submitted as ``priority="batch"``:
  the scheduler sheds batch work to admit interactive arrivals).
  Acceptance: controlled interactive goodput >= 80% of the uncontended
  baseline while the uncontrolled pass visibly degrades.
- ``poison``: 1 poison request + innocents over a 2-supervised-replica
  router (``SupervisedChaos`` keeps the fingerprint fault armed across
  warm restarts). Acceptance: the fleet pays at most
  ``quarantine_crashes`` restarts, the poison fails terminally with the
  quarantine marker, EVERY innocent completes bit-identical to
  ``generation.generate`` (``poison.innocent_completed_frac`` pinned at
  exactly 1.0 in ``perf_baseline.json``), zero retraces.

Artifact: ``benchmarks/bench_overload.json``; ``tests/run_shards.py``
folds it into ``telemetry_lane.json`` as ``overload_bench`` and the
perf gate reads ``overload.supervisor_overhead_pct`` /
``overload.innocent_completed_frac`` from it. Exit code is non-zero
when a verdict fails. CPU numbers size the lane on the dev box; the
chip lane reruns for real ones.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import generation, serving
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import recompile
from paddle_tpu.serving.supervisor import POISON_MARKER

HERE = os.path.dirname(os.path.abspath(__file__))

MAX_SLOTS = 4
MAX_LEN = 96
MODEL_KW = dict(hidden_size=256, intermediate_size=512,
                num_hidden_layers=3, num_attention_heads=8,
                num_key_value_heads=4, vocab_size=2048)

# the supervisor-overhead workload (same shape as bench_router's):
# staggered arrivals, mixed greedy/sampled
OVERHEAD_WORKLOAD = [
    (0.00, 5, dict(max_new_tokens=40)),
    (0.00, 9, dict(max_new_tokens=32, do_sample=True, temperature=0.8,
                   top_k=8, seed=1)),
    (0.03, 14, dict(max_new_tokens=48)),
    (0.06, 26, dict(max_new_tokens=24, do_sample=True, top_p=0.9, seed=2)),
    (0.10, 7, dict(max_new_tokens=40)),
    (0.14, 11, dict(max_new_tokens=24, do_sample=True, temperature=1.1,
                    top_k=12, seed=3)),
    (0.18, 19, dict(max_new_tokens=32)),
    (0.22, 4, dict(max_new_tokens=16)),
    (0.28, 6, dict(max_new_tokens=32)),
    (0.34, 10, dict(max_new_tokens=28)),
]

# overload lane: an interactive stream + a CLOSED-LOOP batch flood — a
# hammering submitter that refills the queue the moment anything
# drains, so the engine runs oversubscribed for the whole interactive
# window no matter how fast the host decodes (an open-loop arrival
# rate would have to be tuned per machine). The two contended passes
# differ ONLY in the flood's priority class.
INTERACTIVE_N = 8
FLOOD_TOKENS = 48
INTERACTIVE_DEADLINE_S = 20.0
MAX_QUEUE_DEPTH = 8
GOODPUT_FLOOR_FRAC = 0.80


def _prompts(cfg, seed, spec):
    rng = np.random.RandomState(seed)
    return [(at, rng.randint(1, cfg.vocab_size, n).astype(np.int32), p)
            for at, n, p in spec]


def serving_retraces():
    return sum(v["retraces"] for k, v in recompile.entry_stats().items()
               if k.startswith("serving."))


def pct(values, q):
    if not values:
        return None
    return float(np.percentile(np.asarray(values), q))


def run_workload(submit, workload, timeout_s=90.0):
    """Time-scheduled submission; rejected submits (shed/backpressure)
    are counted, not fatal — that is the overload contract."""
    handles, rejected = [], 0
    t0 = time.perf_counter()
    for at, prompt, params in workload:
        while time.perf_counter() - t0 < at:
            time.sleep(0.002)
        try:
            handles.append(submit(prompt, params))
        except serving.QueueFullError:
            rejected += 1
    for h in handles:
        try:
            h.result(timeout=timeout_s)
        except TimeoutError:
            pass
    wall = time.perf_counter() - t0
    return handles, rejected, wall


# ---------------------------------------------------------------------------
# lane 1: supervisor overhead on the normal path
# ---------------------------------------------------------------------------

def lane_overhead(model, workload):
    direct = serving.ServingEngine(model, max_slots=MAX_SLOTS,
                                   max_len=MAX_LEN)
    direct.warmup()
    direct.start()
    sup = serving.EngineSupervisor(model, max_slots=MAX_SLOTS,
                                   max_len=MAX_LEN)
    sup.warmup()
    sup.start()

    def make_submit(eng):
        def submit(prompt, params):
            return eng.submit(prompt,
                              params=serving.SamplingParams(**params))
        return submit

    best = {"direct": 0.0, "supervised": 0.0}
    for _ in range(3):
        for name, eng in (("direct", direct), ("supervised", sup)):
            handles, _, wall = run_workload(make_submit(eng), workload)
            tok_s = sum(len(h.output_tokens) for h in handles) / wall
            best[name] = max(best[name], tok_s)
    overhead_pct = 100.0 * (1.0 - best["supervised"] / best["direct"])
    assert sup.restarts == 0  # the normal path never restarted
    direct.stop()
    sup.stop()
    return {"direct_tok_s": round(best["direct"], 1),
            "supervised_tok_s": round(best["supervised"], 1),
            "overhead_pct": round(overhead_pct, 2),
            "passes": 3,
            "verdict_lt_2pct": overhead_pct < 2.0}


# ---------------------------------------------------------------------------
# lane 2: 2x oversubscription, shed vs drown
# ---------------------------------------------------------------------------

def _interactive_pass(eng, cfg, flood_priority, contended):
    """One pass: optional closed-loop flood (at ``flood_priority``) +
    the staggered interactive stream. Returns the interactive-side
    scorecard."""
    stop = threading.Event()
    flood_stats = {"admitted": 0, "bounced": 0}

    def flood_loop():
        rng = np.random.RandomState(13)
        params = dict(max_new_tokens=FLOOD_TOKENS)
        if flood_priority is not None:
            params["priority"] = flood_priority
        while not stop.is_set():
            p = rng.randint(1, cfg.vocab_size,
                            6 + flood_stats["admitted"] % 5)
            try:
                eng.submit(p.astype(np.int32),
                           params=serving.SamplingParams(**params))
                flood_stats["admitted"] += 1
            except serving.QueueFullError:
                flood_stats["bounced"] += 1
                time.sleep(0.002)

    flooder = None
    if contended:
        flooder = threading.Thread(target=flood_loop, daemon=True,
                                   name="bench-overload-flood")
        flooder.start()
        time.sleep(0.1)  # the flood owns the queue before traffic lands

    rng = np.random.RandomState(7)
    inter_handles, rejected = [], 0
    t0 = time.perf_counter()
    for i in range(INTERACTIVE_N):
        while time.perf_counter() - t0 < 0.15 + 0.25 * i:
            time.sleep(0.002)
        try:
            inter_handles.append(eng.submit(
                rng.randint(1, cfg.vocab_size,
                            5 + (i % 4)).astype(np.int32),
                deadline_s=INTERACTIVE_DEADLINE_S,
                params=serving.SamplingParams(max_new_tokens=24)))
        except serving.QueueFullError:
            # the uncontrolled arm's failure mode: a same-class flood
            # leaves no room to shed, so interactive work bounces
            rejected += 1
    for h in inter_handles:
        try:
            h.result(timeout=60.0)
        except TimeoutError:
            pass
    wall = time.perf_counter() - t0
    stop.set()
    if flooder is not None:
        flooder.join(timeout=5.0)
    completed = [h for h in inter_handles
                 if h.status == serving.RequestStatus.COMPLETED]
    good_tokens = sum(len(h.output_tokens) for h in completed)
    ttfts = [h.ttft_s for h in inter_handles if h.ttft_s is not None]
    return {
        "interactive_submitted": INTERACTIVE_N,
        "interactive_admitted": len(inter_handles),
        "interactive_rejected": rejected,
        "interactive_completed": len(completed),
        "interactive_goodput_tok_s": round(good_tokens / wall, 1),
        "interactive_ttft_p95_ms":
            (round(1e3 * pct(ttfts, 95), 1) if ttfts else None),
        "flood_admitted": flood_stats["admitted"],
        "flood_bounced": flood_stats["bounced"],
        "wall_s": round(wall, 3),
    }


def lane_overload(model, cfg):
    """Uncontended baseline, then the 2x flood twice: once drowning the
    interactive class (everything "interactive"), once shed as
    ``priority="batch"``. Fresh engine per pass — queue state must not
    leak across arms."""
    passes = {}
    for name, flood_priority, contended in (
            ("uncontended", None, False),
            ("uncontrolled", None, True),
            ("controlled", "batch", True)):
        eng = serving.ServingEngine(model, max_slots=MAX_SLOTS,
                                    max_len=MAX_LEN,
                                    max_queue_depth=MAX_QUEUE_DEPTH)
        eng.warmup()
        eng.start()
        passes[name] = _interactive_pass(eng, cfg, flood_priority,
                                         contended)
        eng.stop(abort=True, drain_timeout_s=10.0)
    base = passes["uncontended"]["interactive_goodput_tok_s"]
    held = passes["controlled"]["interactive_goodput_tok_s"]
    ratio = held / base if base else 0.0
    p95_base = passes["uncontended"]["interactive_ttft_p95_ms"] or 0.0
    unctl = passes["uncontrolled"]
    # without priority classes the same closed-loop flood visibly hurts
    # the interactive stream: arrivals bounce off the full same-class
    # queue, or the survivors' TTFT tail stretches
    degraded = unctl["interactive_rejected"] > 0 \
        or (unctl["interactive_ttft_p95_ms"] or 0.0) > 1.5 * p95_base
    return {
        "max_queue_depth": MAX_QUEUE_DEPTH,
        "flood_tokens": FLOOD_TOKENS,
        "passes": passes,
        "controlled_vs_uncontended_goodput": round(ratio, 4),
        "verdict_goodput_held": ratio >= GOODPUT_FLOOR_FRAC,
        "verdict_uncontrolled_degraded": degraded,
    }


# ---------------------------------------------------------------------------
# lane 3: the poison crash-loop bill
# ---------------------------------------------------------------------------

def lane_poison(model, cfg):
    quarantine_crashes = 2
    sups = [serving.EngineSupervisor(model, max_slots=MAX_SLOTS,
                                     max_len=MAX_LEN,
                                     quarantine_crashes=quarantine_crashes,
                                     max_restarts=3)
            for _ in range(2)]
    rng = np.random.RandomState(11)
    poison_prompt = rng.randint(1, cfg.vocab_size, 6).astype(np.int32)
    poison_params = serving.SamplingParams(max_new_tokens=16)
    fp = serving.request_fingerprint(poison_prompt, poison_params)
    chaos = [serving.SupervisedChaos(
        s, arm=lambda m: m.poison_fingerprint(fp)) for s in sups]

    innocents = []
    for i in range(12):
        params = dict(max_new_tokens=12)
        if i % 3 == 1:
            params = dict(max_new_tokens=10, do_sample=True, top_k=8,
                          seed=50 + i)
        innocents.append(
            (rng.randint(1, cfg.vocab_size, 4 + (i % 5)).astype(np.int32),
             params))
    refs = [generation.generate(model, p[None], **params)
            .numpy()[0, len(p):] for p, params in innocents]

    router = serving.Router(sups, serving.RouterConfig(
        probe_interval_s=0.05, max_retries_per_request=2,
        unroutable_timeout_s=30.0))
    router.start()
    retr0 = serving_retraces()
    t0 = time.perf_counter()
    rr_poison = router.submit(poison_prompt, params=poison_params)
    rrs = [router.submit(p, params=serving.SamplingParams(**params))
           for p, params in innocents]
    for rr in [rr_poison] + rrs:
        try:
            rr.result(timeout=120.0)
        except TimeoutError:
            pass
    wall = time.perf_counter() - t0
    restarts = sum(s.restarts for s in sups)
    fired = sum(c.injected["poison"] for c in chaos)
    completed = [rr for rr in rrs
                 if rr.status == serving.RequestStatus.COMPLETED]
    parity = all(np.array_equal(np.asarray(rr.output_tokens), ref)
                 for rr, ref in zip(rrs, refs)
                 if rr.status == serving.RequestStatus.COMPLETED)
    quarantined = sorted(set(sups[0].quarantined + sups[1].quarantined))
    new_retraces = serving_retraces() - retr0
    router.stop(drain=True, timeout_s=30)
    return {
        "innocents": len(rrs),
        "innocent_completed": len(completed),
        "innocent_completed_frac": round(len(completed) / len(rrs), 4),
        "innocent_parity": parity,
        "poison_status": rr_poison.status,
        "poison_marker_in_error": bool(rr_poison.error
                                       and POISON_MARKER in rr_poison.error),
        "poison_fired": fired,
        "quarantine_crashes_budget": quarantine_crashes,
        "fleet_restarts": restarts,
        "quarantined_fingerprints": quarantined,
        "new_retraces": new_retraces,
        "wall_s": round(wall, 3),
        "verdict_restarts_bounded": restarts <= quarantine_crashes,
        "verdict_all_innocents": len(completed) == len(rrs),
    }


def main():
    paddle.seed(0)
    cfg = LlamaConfig(**MODEL_KW)
    model = LlamaForCausalLM(cfg)
    print(f"[bench_overload] model {MODEL_KW['hidden_size']}h x "
          f"{MODEL_KW['num_hidden_layers']}L", flush=True)

    workload = _prompts(cfg, 42, OVERHEAD_WORKLOAD)
    overhead = lane_overhead(model, workload)
    print(f"[bench_overload] supervisor tax: direct "
          f"{overhead['direct_tok_s']} tok/s vs supervised "
          f"{overhead['supervised_tok_s']} tok/s -> "
          f"{overhead['overhead_pct']}% (<2% verdict: "
          f"{overhead['verdict_lt_2pct']})", flush=True)

    overload = lane_overload(model, cfg)
    p = overload["passes"]
    print(f"[bench_overload] overload: interactive goodput uncontended "
          f"{p['uncontended']['interactive_goodput_tok_s']} tok/s, "
          f"uncontrolled {p['uncontrolled']['interactive_goodput_tok_s']} "
          f"tok/s, controlled "
          f"{p['controlled']['interactive_goodput_tok_s']} tok/s "
          f"({overload['controlled_vs_uncontended_goodput']:.2f}x of "
          f"baseline; held: {overload['verdict_goodput_held']})",
          flush=True)
    print(f"[bench_overload] interactive TTFT p95: uncontended "
          f"{p['uncontended']['interactive_ttft_p95_ms']} ms, "
          f"uncontrolled {p['uncontrolled']['interactive_ttft_p95_ms']} "
          f"ms, controlled {p['controlled']['interactive_ttft_p95_ms']} "
          f"ms", flush=True)

    poison = lane_poison(model, cfg)
    print(f"[bench_overload] poison: {poison['fleet_restarts']} fleet "
          f"restarts (budget {poison['quarantine_crashes_budget']}), "
          f"{poison['innocent_completed']}/{poison['innocents']} "
          f"innocents completed, parity {poison['innocent_parity']}, "
          f"new retraces {poison['new_retraces']}", flush=True)

    verdicts = {
        "supervisor_overhead_lt_2pct": overhead["verdict_lt_2pct"],
        "interactive_goodput_held": overload["verdict_goodput_held"],
        "uncontrolled_degraded": overload["verdict_uncontrolled_degraded"],
        "poison_restarts_bounded": poison["verdict_restarts_bounded"],
        "poison_quarantined": poison["poison_status"] == "failed"
        and poison["poison_marker_in_error"],
        "poison_fault_fired": poison["poison_fired"] >= 1,
        "all_innocents_completed": poison["verdict_all_innocents"],
        "innocent_parity": poison["innocent_parity"],
        "zero_retraces": poison["new_retraces"] == 0,
    }
    out = {
        "model": MODEL_KW,
        "max_slots": MAX_SLOTS,
        "overhead": overhead,
        "overload": overload,
        "poison": poison,
        "verdicts": verdicts,
    }
    path = os.path.join(HERE, "bench_overload.json")
    with open(path, "w") as fh:
        json.dump(out, fh, indent=1)
    print(f"[bench_overload] -> {path}", flush=True)
    failed = [k for k, v in verdicts.items() if not v]
    if failed:
        print(f"[bench_overload] VERDICTS FAILED: {failed}", flush=True)
        return 1
    print("[bench_overload] all verdicts passed", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
