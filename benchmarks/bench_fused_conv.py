"""Fused conv+BN+ReLU microbench: Pallas kernels vs the XLA-fused path.

Run on a real TPU chip (`python benchmarks/bench_fused_conv.py`).
Prints one JSON line per ResNet-50 hot shape with:

- ``eval``: inference epilogue kernel (conv+scale/shift+relu, one HBM
  write) vs the XLA composition conv -> BN(frozen stats) -> relu.
- ``train``: fwd+bwd of conv+BN with batch stats (the Pallas path
  computes stats in the conv epilogue and, in the chained variant,
  consumes the upstream normalize+relu as a VMEM prologue) vs the XLA
  composition, both through jax.value_and_grad.
- ``bytes_saved_mb``: per-block HBM savings from the committed round-5
  byte audit (benchmarks/resnet_byte_audit.json).

Timing: the same chained-scan differencing as bench_flash_attention.py
(the only honest method on a remote PJRT transport — see that module's
docstring); iteration outputs feed back into the inputs via a scalar
epsilon so the scan can be neither parallelized nor elided.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.pallas_kernels.fused_conv import (_xla_conv, bn_apply,
                                                  conv_stats, conv_stats_pre,
                                                  fused_conv_bn_eval)

ON_TPU = jax.default_backend() == "tpu"
# ResNet-50 hot NHWC shapes (batch matches the flagship bench point);
# CPU fallback uses tiny shapes in interpret mode — correctness smoke
# only, the timings are meaningless off-chip.
BATCH = 256 if ON_TPU else 4
SHAPES = [
    # (tag, H=W, C_in, C_out, k)
    ("l1.conv2 3x3", 56, 64, 64, 3),
    ("l2.conv2 3x3", 28, 128, 128, 3),
    ("l3.conv2 3x3", 14, 256, 256, 3),
    ("l4.conv2 3x3", 7, 512, 512, 3),
    ("l1.conv1 1x1", 56, 256, 64, 1),
    ("l3.conv3 1x1", 14, 256, 1024, 1),
    ("l4.conv1 1x1", 7, 2048, 512, 1),
] if ON_TPU else [
    ("3x3 smoke", 8, 16, 16, 3),
    ("1x1 smoke", 8, 32, 16, 1),
]
DTYPE = jnp.bfloat16 if ON_TPU else jnp.float32


def bench(fn, *args, iters=10):
    """Chained-scan differencing; fn returns a pytree — its leaves' means
    perturb the carried inputs so iterations are serially dependent."""

    def chained(n):
        @jax.jit
        def run(args):
            def body(carry, _):
                out = fn(*carry)
                leaves = jax.tree.leaves(out)
                eps = sum(jnp.mean(l.astype(jnp.float32)) for l in leaves) * 1e-6
                new = tuple(a + eps.astype(a.dtype) for a in carry)
                return new, ()

            carry, _ = jax.lax.scan(body, tuple(args), None, length=n)
            return carry[0]

        _ = np.asarray(jax.device_get(run(args)))[0].ravel()[0]  # compile+warm
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            _ = np.asarray(jax.device_get(run(args)))[0].ravel()[0]
            best = min(best, time.perf_counter() - t0)
        return best

    t1 = chained(1)
    tk = chained(iters + 1)
    return max(tk - t1, 1e-9) / iters


def _audit_savings():
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "resnet_byte_audit.json")
    try:
        with open(path) as f:
            audit = json.load(f)
    except OSError:
        return {}, None
    per_shape = {}
    for b in audit["blocks"]:
        key = (b["conv"], b["out_spatial"], b["in_channels"], b["out_channels"])
        per_shape.setdefault(key, 0)
        per_shape[key] += b["fused_train_fwd_bytes_saved"]
    return per_shape, audit["per_block_activation_model"]


def main():
    rng = np.random.RandomState(0)
    savings, agg = _audit_savings()

    for tag, hw, c, k, ksz in SHAPES:
        x = jnp.asarray(rng.randn(BATCH, hw, hw, c), DTYPE)
        w = jnp.asarray(rng.randn(k, c, ksz, ksz) * 0.05, DTYPE)
        scale = jnp.asarray(rng.rand(k) + 0.5, jnp.float32)
        shift = jnp.asarray(rng.randn(k), jnp.float32)
        gamma = jnp.asarray(rng.rand(k) + 0.5, jnp.float32)
        beta = jnp.asarray(rng.randn(k), jnp.float32)
        # upstream-unit tensors for the chained (prologue) variant
        m_p = jnp.asarray(rng.randn(c) * 0.1, jnp.float32)
        v_p = jnp.asarray(rng.rand(c) + 0.5, jnp.float32)
        gp = jnp.asarray(rng.rand(c) + 0.5, jnp.float32)
        bp = jnp.asarray(rng.randn(c), jnp.float32)

        # --- inference epilogue ---
        def eval_fused(x, w):
            return fused_conv_bn_eval(x, w, scale, shift, True)

        def eval_xla(x, w):
            y = _xla_conv(x, w) * scale + shift
            return jnp.maximum(y, 0.0).astype(x.dtype)

        t_eval_fused = bench(eval_fused, x, w)
        t_eval_xla = bench(eval_xla, x, w)

        # --- training fwd+bwd (loss = sum of normalized output) ---
        def train_fused(x, w):
            def loss(x, w):
                co, m, v = conv_stats(x, w)
                return jnp.sum(bn_apply(co, m, v, gamma, beta, 1e-5)
                               .astype(jnp.float32))

            return jax.value_and_grad(loss, (0, 1))(x, w)

        def train_chained(x, w):
            def loss(x, w):
                co, m, v = conv_stats_pre(x, m_p, v_p, gp, bp, w, True, 1e-5)
                return jnp.sum(bn_apply(co, m, v, gamma, beta, 1e-5)
                               .astype(jnp.float32))

            return jax.value_and_grad(loss, (0, 1))(x, w)

        def train_xla(x, w):
            def loss(x, w):
                co = _xla_conv(x, w).astype(jnp.float32)
                m, v = co.mean((0, 1, 2)), co.var((0, 1, 2))
                y = (co - m) * jax.lax.rsqrt(v + 1e-5) * gamma + beta
                return jnp.sum(y)

            return jax.value_and_grad(loss, (0, 1))(x, w)

        t_train_fused = bench(train_fused, x, w)
        t_train_chained = bench(train_chained, x, w)
        t_train_xla = bench(train_xla, x, w)

        key = (f"{ksz}x{ksz}/s1", hw, c, k)
        print(json.dumps({
            "shape": tag, "batch": BATCH, "hw": hw, "cin": c, "cout": k,
            "dtype": str(DTYPE.__name__),
            "eval_ms": {"pallas_fused": round(t_eval_fused * 1e3, 3),
                        "xla": round(t_eval_xla * 1e3, 3),
                        "speedup": round(t_eval_xla / t_eval_fused, 3)},
            "train_ms": {"pallas_fused": round(t_train_fused * 1e3, 3),
                         "pallas_chained": round(t_train_chained * 1e3, 3),
                         "xla": round(t_train_xla * 1e3, 3),
                         "speedup": round(t_train_xla / t_train_fused, 3),
                         "speedup_chained": round(t_train_xla / t_train_chained, 3)},
            "audit_train_fwd_bytes_saved_mb":
                round(savings.get(key, 0) / 2**20, 1) if savings else None,
        }), flush=True)

    if agg:
        print(json.dumps({"resnet50_audit_aggregate": agg}), flush=True)
    if not ON_TPU:
        print(json.dumps({"note": "CPU interpret-mode run: correctness smoke "
                                  "only, timings are not meaningful"}),
              flush=True)


if __name__ == "__main__":
    main()
