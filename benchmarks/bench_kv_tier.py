"""Hierarchical-KV tier lane: recompute-elimination A/B + restart.

Three workloads, each run with the host tier OFF and ON against the
SAME (deliberately small) device block pool, so prefix-cache eviction
pressure is real and the tier is what decides whether evicted work is
recomputed or re-admitted:

1. **Long conversation** — the tentpole claim (the CachedAttention /
   Mooncake workload). One multi-turn conversation whose context grows
   every turn; between turns the prefix cache is LRU-rolled (the
   deterministic stand-in for the tenant traffic that evicts idle
   conversations in production). Tier OFF, every turn re-prefills the
   entire history; tier ON, the evicted blocks demote to host RAM and
   the next turn re-admits them via the jitted splice. The bench
   measures RECOMPUTE prefill tokens per turn — computed tokens (the
   engine's ``prompt`` counter; cached/tier-readmitted tokens never
   hit it) minus the turn's genuinely-new tokens (last reply + new
   user turn), which no tier can eliminate — and asserts the tier
   eliminates **>= 80%** of the recompute, at bit parity (greedy and
   sampled) with the tier-off outputs.
2. **Many tenants** — N tenants with private system prefixes take
   turns; the pool only holds a few of them at once. Same metric, same
   parity oracle: the tier turns tenant-return recompute into
   re-admission.
3. **Restart** — a conversation runs, the engine stops (drain flushes
   the host tier through the atomic-commit disk store), a NEW engine on
   the same ``kv_tier_path`` continues it: the follow-up turn re-admits
   from DISK and its output bit-matches the uninterrupted run.

The exit code enforces parity on every lane, the >= 80% long-
conversation saving, >0 disk readmits after restart, and ZERO retraces
of the four serving executables (step / prefill_chunk / kv_demote /
kv_splice) across all lanes.

Artifact: ``benchmarks/bench_kv_tier.json``; ``tests/run_shards.py``
folds it into ``telemetry_lane.json`` as the ``kv_tier_bench`` block
(both lanes).
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import serving
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import recompile
from paddle_tpu.serving import metrics as _sm

HERE = os.path.dirname(os.path.abspath(__file__))

MODEL_KW = dict(hidden_size=128, intermediate_size=256,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, vocab_size=1024,
                max_position_embeddings=256)

MAX_LEN = 224
BLOCK_SIZE = 8
# small on purpose: ~2 conversations' worth of blocks, so filler
# traffic between turns ALWAYS evicts the conversation's prefix
NUM_BLOCKS = 56
HOST_BLOCKS = 512           # the host tier holds everything evicted

TURNS = 5
TURN_USER_TOKENS = 12       # new user tokens per turn
TURN_REPLY_TOKENS = 8       # generated reply folded into the context

TENANTS = 8
TENANT_PREFIX = 56
TENANT_TURNS = 2
SEED = 20240806


def _engine(model, *, tier, path=None, num_blocks=NUM_BLOCKS):
    eng = serving.ServingEngine(
        model, max_slots=4, max_len=MAX_LEN, block_size=BLOCK_SIZE,
        num_blocks=num_blocks, kv_tier=tier, kv_tier_path=path,
        kv_tier_host_blocks=HOST_BLOCKS)
    eng.warmup()
    return eng


def _counters():
    return {
        "prompt": _sm.tokens_total.labels("prompt").value(),
        "cached": _sm.tokens_total.labels("prompt_cached").value(),
        "tier": _sm.tokens_total.labels("prompt_tier").value(),
    }


def _delta(before):
    after = _counters()
    return {k: after[k] - before[k] for k in before}


def _run(eng, prompt, *, sampled, seed, max_new):
    params = dict(max_new_tokens=max_new, seed=seed)
    if sampled:
        params.update(do_sample=True, temperature=0.8, top_k=16)
    req = eng.submit(np.asarray(prompt, np.int32), **params)
    eng.run_until_idle(max_steps=20000)
    assert req.status == serving.RequestStatus.COMPLETED, req.status
    return list(np.asarray(req.result(timeout=10.0)))


def run_long_conversation(model, *, tier, path=None):
    eng = _engine(model, tier=tier, path=path)
    rng = np.random.RandomState(SEED)
    ctx = list(rng.randint(1, MODEL_KW["vocab_size"], 24))
    before = _counters()
    outs = []
    computed, recompute = 0.0, 0.0
    t0 = time.perf_counter()
    prev_len = 0
    for turn in range(TURNS):
        ctx += list(rng.randint(1, MODEL_KW["vocab_size"],
                                TURN_USER_TOKENS))
        t_before = _counters()
        reply = _run(eng, ctx, sampled=bool(turn % 2), seed=turn,
                     max_new=TURN_REPLY_TOKENS)
        turn_computed = _delta(t_before)["prompt"]
        new_tokens = len(ctx) - prev_len  # last reply + this user turn
        computed += turn_computed
        recompute += max(0.0, turn_computed - new_tokens)
        prev_len = len(ctx)
        outs.append(reply)
        ctx += reply
        # roll the LRU cache: what production tenant churn does between
        # a conversation's turns (tier off: the work is gone; tier on:
        # every evicted block demotes through the on_evict hook)
        eng.prefix_cache.evict(eng.pool.num_blocks)
    wall = time.perf_counter() - t0
    toks = _delta(before)
    st = eng.stats()
    eng.stop()
    return {
        "tier": tier,
        "turns": TURNS,
        "wall_s": round(wall, 3),
        "prefill_tokens_computed": toks["prompt"],
        "recompute_prefill_tokens": recompute,
        "prefix_cached_tokens": toks["cached"],
        "tier_readmitted_tokens": toks["tier"],
        "kv_tier": st.get("kv_tier"),
    }, outs, ctx


def run_many_tenants(model, *, tier):
    eng = _engine(model, tier=tier)
    rng = np.random.RandomState(SEED + 1)
    prefixes = [list(rng.randint(1, MODEL_KW["vocab_size"], TENANT_PREFIX))
                for _ in range(TENANTS)]
    before = _counters()
    outs = []
    t0 = time.perf_counter()
    for rnd in range(TENANT_TURNS):
        for t, pfx in enumerate(prefixes):
            tail = list(rng.randint(1, MODEL_KW["vocab_size"], 6))
            outs.append(_run(eng, pfx + tail, sampled=bool(t % 2),
                             seed=rnd * TENANTS + t, max_new=6))
    wall = time.perf_counter() - t0
    toks = _delta(before)
    st = eng.stats()
    eng.stop()
    return {
        "tier": tier,
        "tenants": TENANTS,
        "rounds": TENANT_TURNS,
        "wall_s": round(wall, 3),
        "prefill_tokens_computed": toks["prompt"],
        "prefix_cached_tokens": toks["cached"],
        "tier_readmitted_tokens": toks["tier"],
        "kv_tier": st.get("kv_tier"),
    }, outs


def run_restart(model, tmp):
    """Conversation -> stop (disk flush) -> NEW engine, same path ->
    the follow-up turn re-admits from disk; output bit-matches the
    same turn on an uninterrupted engine."""
    rng = np.random.RandomState(SEED + 2)
    ctx = list(rng.randint(1, MODEL_KW["vocab_size"], 40))
    follow = list(rng.randint(1, MODEL_KW["vocab_size"], 8))

    # uninterrupted reference (tier off: pure recompute semantics)
    eng = _engine(model, tier=False)
    _run(eng, ctx, sampled=False, seed=0, max_new=6)
    ref = _run(eng, ctx + follow, sampled=True, seed=1, max_new=8)
    eng.stop()

    path = os.path.join(tmp, "tier")
    eng1 = _engine(model, tier=True, path=path)
    _run(eng1, ctx, sampled=False, seed=0, max_new=6)
    eng1.stop()                       # drain flush -> committed entries

    eng2 = _engine(model, tier=True, path=path)
    before = _counters()
    out = _run(eng2, ctx + follow, sampled=True, seed=1, max_new=8)
    toks = _delta(before)
    st = eng2.stats()["kv_tier"]
    eng2.stop()
    return {
        "disk_entries_found": st["disk"]["entries"],
        "disk_loads": st["disk"]["loads"],
        "tier_readmitted_tokens": toks["tier"],
        "prefill_tokens_computed": toks["prompt"],
        "parity": out == ref,
    }


def main():
    paddle.seed(0)
    cfg = LlamaConfig.tiny(**MODEL_KW)
    model = LlamaForCausalLM(cfg)

    stats0 = {k: dict(v) for k, v in recompile.entry_stats().items()}

    lc_off, lc_outs_off, _ = run_long_conversation(model, tier=False)
    lc_on, lc_outs_on, _ = run_long_conversation(model, tier=True)
    mt_off, mt_outs_off = run_many_tenants(model, tier=False)
    mt_on, mt_outs_on = run_many_tenants(model, tier=True)
    tmp = tempfile.mkdtemp(prefix="bench_kv_tier_")
    try:
        restart = run_restart(model, tmp)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    saved_lc = 1.0 - (lc_on["recompute_prefill_tokens"]
                      / max(1.0, lc_off["recompute_prefill_tokens"]))
    saved_mt = 1.0 - (mt_on["prefill_tokens_computed"]
                      / max(1, mt_off["prefill_tokens_computed"]))
    speedup_lc = lc_off["wall_s"] / max(1e-9, lc_on["wall_s"])

    stats1 = recompile.entry_stats()
    retraces = {
        name: stats1[name]["retraces"]
        - stats0.get(name, {}).get("retraces", 0)
        for name in ("serving.step", "serving.prefill_chunk",
                     "serving.kv_demote", "serving.kv_splice")
        if name in stats1}

    verdicts = {
        "longconv_saved_ge_80pct": saved_lc >= 0.80,
        "parity_longconv": lc_outs_off == lc_outs_on,
        "parity_many_tenant": mt_outs_off == mt_outs_on,
        "restart_parity": restart["parity"],
        "restart_disk_readmit": restart["disk_loads"] > 0
        and restart["tier_readmitted_tokens"] > 0,
        "zero_retrace": all(v == 0 for v in retraces.values())
        and "serving.kv_splice" in retraces,
    }
    result = {
        "bench": "kv_tier",
        "platform": jax.default_backend(),
        "model": {"family": "llama", **MODEL_KW},
        "pool": {"num_blocks": NUM_BLOCKS, "block_size": BLOCK_SIZE,
                 "host_blocks": HOST_BLOCKS},
        "long_conversation": {
            "off": lc_off, "on": lc_on,
            "saved_frac": round(saved_lc, 4),
            "readmit_speedup": round(speedup_lc, 3)},
        "many_tenant": {
            "off": mt_off, "on": mt_on,
            "saved_frac": round(saved_mt, 4)},
        "restart": restart,
        "retraces": retraces,
        "parity_all": bool(verdicts["parity_longconv"]
                           and verdicts["parity_many_tenant"]
                           and verdicts["restart_parity"]),
        "verdicts": verdicts,
    }
    path = os.path.join(HERE, "bench_kv_tier.json")
    with open(path, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps(result, indent=1))
    print(f"[bench_kv_tier] artifact -> {path}")
    ok = all(verdicts.values())
    if not ok:
        print("[bench_kv_tier] ACCEPTANCE FAILED", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
