"""Flash-decode A/B lane: Pallas kernel vs XLA fallback on the decode step.

The serving decode hot loop's attention, isolated: single-query
attention over a static [B, max_len, kv_heads, d] KV cache at three
cache occupancies (25/50/100% — per-row positions, the continuous-
batching steady state) and two GQA ratios (1x and 4x), timed three ways:

- ``kernel``:   pallas_kernels.decode_attention.flash_decode_attention
                (split-K grid, GQA-native, per-row length masking);
- ``fallback``: the post-PR XLA path — grouped-einsum SDPA over the
                masked cache (nn.functional.grouped_query_sdpa form),
                no repeat_kv materialization;
- ``legacy``:   the pre-PR XLA path — repeat_kv-expanded K/V + dense
                masked SDPA (what every decode step used to pay).

All three are jitted on raw jnp arrays, warmed, and timed best-of-N
with block_until_ready. Parity (kernel vs fallback) is asserted per
config.

Artifact: ``benchmarks/bench_decode.json`` — per-config ms + speedups +
max parity error; ``tests/run_shards.py`` folds it into
``telemetry_lane.json`` as the ``decode_bench`` block for both lanes.

Lane semantics: on CPU the Pallas kernel runs in the INTERPRETER, so
this lane records interpret-mode parity only (timings are reported but
the speedup acceptance is not applied — the interpreter is orders of
magnitude off). On TPU (`--platform=tpu` chip lane) the acceptance is
kernel >= 1.3x over the fallback on the GQA-4x config at <= 50%
occupancy.
"""

from __future__ import annotations

import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.pallas_kernels.decode_attention import flash_decode_attention

HERE = os.path.dirname(os.path.abspath(__file__))

ON_TPU = jax.default_backend() == "tpu"
# CPU shapes keep the interpreted kernel tractable; chip shapes are the
# serving regime (Llama-70B-style head geometry, 2k cache)
if ON_TPU:
    B, KV, D, MAX_LEN, Q_LEN, BLOCK_K = 8, 2, 128, 2048, 1, 256
else:
    B, KV, D, MAX_LEN, Q_LEN, BLOCK_K = 4, 2, 64, 512, 1, 64

GQA_RATIOS = (1, 4)
OCCUPANCIES = (0.25, 0.5, 1.0)
ACCEPT_SPEEDUP = 1.3  # TPU lane: kernel vs fallback, GQA 4x, occ <= 0.5


def _mask_for(pos, q_len, max_len):
    """The update_static_kv_cache per-row additive mask the XLA paths pay."""
    kpos = jnp.arange(max_len)
    qpos = pos[:, None] + jnp.arange(q_len)
    m = (kpos[None, None, :] <= qpos[:, :, None]) \
        & (kpos[None, None, :] < (pos[:, None, None] + q_len))
    return jnp.where(m[:, None], 0.0, -1e30).astype(jnp.float32)


def _grouped_sdpa(q, kc, vc, mask):
    b, s, H, d = q.shape
    kv = kc.shape[2]
    g = H // kv
    qt = jnp.swapaxes(q, 1, 2).reshape(b, kv, g, s, d)
    kt = jnp.swapaxes(kc, 1, 2)
    vt = jnp.swapaxes(vc, 1, 2)
    scores = jnp.einsum("bkgqd,bktd->bkgqt", qt, kt) / math.sqrt(d)
    scores = scores + mask[:, :, None]
    p = jax.nn.softmax(scores.astype(jnp.float32), -1).astype(vt.dtype)
    out = jnp.einsum("bkgqt,bktd->bkgqd", p, vt)
    return jnp.swapaxes(out.reshape(b, H, s, d), 1, 2)


def _legacy_sdpa(q, kc, vc, mask):
    b, s, H, d = q.shape
    g = H // kc.shape[2]
    ke = jnp.repeat(kc, g, axis=2)  # the old HBM-materialized expansion
    ve = jnp.repeat(vc, g, axis=2)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(ke, 1, 2)
    vt = jnp.swapaxes(ve, 1, 2)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) / math.sqrt(d)
    scores = scores + mask
    p = jax.nn.softmax(scores.astype(jnp.float32), -1).astype(vt.dtype)
    return jnp.swapaxes(jnp.einsum("bhqk,bhkd->bhqd", p, vt), 1, 2)


def _time(fn, *args, iters=30, warmup=3):
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best * 1e3  # ms


def run_config(gqa, occ, dtype):
    H = KV * gqa
    rng = np.random.RandomState(hash((gqa, int(occ * 100))) % (2 ** 31))
    q = jnp.asarray(rng.randn(B, Q_LEN, H, D), dtype)
    kc = jnp.asarray(rng.randn(B, MAX_LEN, KV, D), dtype)
    vc = jnp.asarray(rng.randn(B, MAX_LEN, KV, D), dtype)
    pos = jnp.asarray(np.full(B, int(occ * MAX_LEN) - Q_LEN, np.int32))

    kern = jax.jit(lambda q, k, v, p: flash_decode_attention(
        q, k, v, p, block_k=BLOCK_K))
    fall = jax.jit(lambda q, k, v, p: _grouped_sdpa(
        q, k, v, _mask_for(p, Q_LEN, MAX_LEN)))
    legacy = jax.jit(lambda q, k, v, p: _legacy_sdpa(
        q, k, v, _mask_for(p, Q_LEN, MAX_LEN)))

    out_k = np.asarray(kern(q, kc, vc, pos), np.float32)
    out_f = np.asarray(fall(q, kc, vc, pos), np.float32)
    max_err = float(np.abs(out_k - out_f).max())

    kernel_ms = _time(kern, q, kc, vc, pos)
    fallback_ms = _time(fall, q, kc, vc, pos)
    legacy_ms = _time(legacy, q, kc, vc, pos)
    tol = 5e-5 if dtype == "float32" else 3e-2
    return {
        "gqa": gqa,
        "occupancy": occ,
        "kernel_ms": round(kernel_ms, 4),
        "fallback_ms": round(fallback_ms, 4),
        "legacy_repeat_kv_ms": round(legacy_ms, 4),
        "kernel_vs_fallback": round(fallback_ms / kernel_ms, 2),
        "fallback_vs_legacy": round(legacy_ms / fallback_ms, 2),
        "max_err": max_err,
        "parity": bool(max_err < tol),
    }


def run_format_config(gqa, occ, dtype):
    """Quantized-cache columns at one config: the dequant-prologue
    kernel per format vs the float kernel on the SAME (dequantized)
    values — per-format ms, max-abs-err, and the KV byte accounting
    that drives the capacity story (bf16 2 bytes/value vs 1 byte +
    4/d scale tax)."""
    from paddle_tpu.quantization import intx

    H = KV * gqa
    rng = np.random.RandomState(77)
    q = jnp.asarray(rng.randn(B, Q_LEN, H, D), dtype)
    kc = jnp.asarray(rng.randn(B, MAX_LEN, KV, D), dtype)
    vc = jnp.asarray(rng.randn(B, MAX_LEN, KV, D), dtype)
    pos = jnp.asarray(np.full(B, int(occ * MAX_LEN) - Q_LEN, np.int32))

    base = jax.jit(lambda q, k, v, p: flash_decode_attention(
        q, k, v, p, block_k=BLOCK_K))
    base_ms = _time(base, q, kc, vc, pos)
    out_base = np.asarray(base(q, kc, vc, pos), np.float32)
    rows = {"bf16" if dtype == "bfloat16" else "float32": {
        "kernel_ms": round(base_ms, 4),
        "kv_bytes_per_value": jnp.dtype(dtype).itemsize,
        "max_abs_err_vs_float": 0.0}}
    formats = ["int8"] + (["fp8"] if intx.fp8_available() else [])
    for fmt in formats:
        ks = intx.absmax_along(kc, -1)
        vs = intx.absmax_along(vc, -1)
        kq = intx.pack_absmax(kc, ks[..., None], fmt)
        vq = intx.pack_absmax(vc, vs[..., None], fmt)
        kern = jax.jit(lambda q, k, v, ks, vs, p: flash_decode_attention(
            q, k, v, p, block_k=BLOCK_K, k_scale=ks, v_scale=vs))
        out_q = np.asarray(kern(q, kq, vq, ks, vs, pos), np.float32)
        rows[fmt] = {
            "kernel_ms": round(_time(kern, q, kq, vq, ks, vs, pos), 4),
            # 1 byte/value + f32 scale amortized over the head_dim
            "kv_bytes_per_value": round(1 + 4 / D, 4),
            "max_abs_err_vs_float": float(np.abs(out_q - out_base).max()),
        }
        rows[fmt]["kv_bytes_vs_bf16"] = round(
            2 / rows[fmt]["kv_bytes_per_value"], 3)
    return {"gqa": gqa, "occupancy": occ, "formats": rows}


def main():
    dtype = "bfloat16" if ON_TPU else "float32"
    rows = [run_config(g, o, dtype) for g in GQA_RATIOS for o in OCCUPANCIES]
    fmt_rows = [run_format_config(4, 0.5, dtype)]

    parity_ok = all(r["parity"] for r in rows)
    accept_rows = [r for r in rows if r["gqa"] == 4 and r["occupancy"] <= 0.5]
    speedup_ok = all(r["kernel_vs_fallback"] >= ACCEPT_SPEEDUP
                     for r in accept_rows)
    result = {
        "bench": "flash_decode_vs_xla",
        "platform": jax.default_backend(),
        "dtype": dtype,
        "shapes": {"batch": B, "kv_heads": KV, "head_dim": D,
                   "max_len": MAX_LEN, "q_len": Q_LEN, "block_k": BLOCK_K},
        "configs": rows,
        "quantized_kv": fmt_rows,
        "parity": parity_ok,
        "speedup_target": ACCEPT_SPEEDUP,
        "speedup_ok": speedup_ok,
        # CPU: the kernel runs in the Pallas INTERPRETER — timings are
        # recorded for the curious but only parity gates the lane; the
        # >=1.3x acceptance applies on the TPU lane
        "mode": "compiled" if ON_TPU else "interpret (parity only)",
    }
    path = os.path.join(HERE, "bench_decode.json")
    with open(path, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps(result, indent=1))
    print(f"[bench_decode_attention] artifact -> {path}")

    ok = parity_ok and (speedup_ok or not ON_TPU)
    if not ok:
        print("[bench_decode_attention] ACCEPTANCE FAILED", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
