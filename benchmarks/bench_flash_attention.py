"""Flash-attention microbench: Pallas kernel vs XLA dense attention.

Run on a real TPU chip (`python benchmarks/bench_flash_attention.py`).
Prints one JSON line per sequence length with fwd/bwd times for the
Pallas flash kernel and the XLA dense reference. Throughput-style
timing (enqueue N, sync once) — the realistic dispatch regime under jit.

Reference analogue: the perf harnesses in test/legacy_test/benchmark.py;
kernel parity: phi/kernels/gpu/flash_attn_kernel.cu / flash_attn_grad_kernel.cu.
"""

from __future__ import annotations

import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.pallas_kernels.flash_attention import _flash


def xla_attn(q, k, v, scale):
    s_ = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                    k.astype(jnp.float32)) * scale
    n = q.shape[1]
    mask = jnp.tril(jnp.ones((n, n), bool))
    s_ = jnp.where(mask, s_, -1e30)
    p = jax.nn.softmax(s_, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p.astype(v.dtype), v)


def bench(fn, *args, iters=10):
    r = fn(*args)
    jax.block_until_ready(r)
    t0 = time.perf_counter()
    for _ in range(iters):
        r = fn(*args)
    jax.block_until_ready(r)
    dt = (time.perf_counter() - t0) / iters
    if dt < 1e-4:
        # async-dispatch artifact guard (r03 judge run saw 0.03 ms for a
        # 4096-seq backward): these kernels are >1 ms of real work, so a
        # ~0 measurement means the sync didn't cover the stream — fall
        # back to per-iteration blocking (latency regime, still honest)
        t0 = time.perf_counter()
        for _ in range(iters):
            jax.block_until_ready(fn(*args))
        dt = (time.perf_counter() - t0) / iters
    return dt


def main():
    d = 64
    for s, bh in ((1024, 192), (2048, 96), (4096, 32)):
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(bh, s, d), jnp.bfloat16)
        k = jnp.asarray(rng.randn(bh, s, d), jnp.bfloat16)
        v = jnp.asarray(rng.randn(bh, s, d), jnp.bfloat16)
        do = jnp.asarray(rng.randn(bh, s, d), jnp.bfloat16)
        scale = 1.0 / math.sqrt(d)

        # 512x512 blocks: the production default flash_attention() uses
        bq = bk = min(512, s)
        flash_f = jax.jit(lambda q, k, v: _flash(q, k, v, None, True, scale, bq, bk))
        xla_f = jax.jit(lambda q, k, v: xla_attn(q, k, v, scale))
        flash_g = jax.jit(jax.grad(
            lambda q, k, v: (_flash(q, k, v, None, True, scale, bq, bk) * do).sum(),
            argnums=(0, 1, 2)))
        xla_g = jax.jit(jax.grad(
            lambda q, k, v: (xla_attn(q, k, v, scale) * do).sum(), argnums=(0, 1, 2)))

        err = float(jnp.abs(flash_f(q, k, v).astype(jnp.float32)
                            - xla_f(q, k, v).astype(jnp.float32)).max())
        row = {
            "seq": s, "bh": bh, "head_dim": d, "max_abs_err": round(err, 4),
            "fwd_flash_ms": round(bench(flash_f, q, k, v) * 1e3, 2),
            "fwd_xla_ms": round(bench(xla_f, q, k, v) * 1e3, 2),
            "bwd_flash_ms": round(bench(flash_g, q, k, v) * 1e3, 2),
            "bwd_xla_ms": round(bench(xla_g, q, k, v) * 1e3, 2),
        }
        row["speedup_fwd"] = round(row["fwd_xla_ms"] / row["fwd_flash_ms"], 2)
        row["speedup_bwd"] = round(row["bwd_xla_ms"] / row["bwd_flash_ms"], 2)
        print(json.dumps(row))


if __name__ == "__main__":
    main()
