"""Flash-attention microbench: Pallas kernel vs XLA dense attention.

Run on a real TPU chip (`python benchmarks/bench_flash_attention.py`).
Prints one JSON line per sequence length with fwd/bwd times for the
Pallas flash kernel and the XLA dense reference.

Timing method: K data-chained iterations inside ONE jitted scan, synced
by a host transfer, minus the same measurement at K=1 — per-iteration
time = (T_K - T_1) / (K - 1). This is the only method that measures
honestly on a remote PJRT transport: jax.block_until_ready returns
early there (r03's judge run recorded 0.03 ms for a 4096-seq backward;
re-measured 2026-07-31, even per-iteration block_until_ready reported
0.05 ms for what a chained-transfer measurement shows is >3 ms), and a
bare host transfer carries a ~100 ms round-trip that would swamp the
kernel. Chaining forces serial execution; differencing cancels the
transfer latency and scan overhead.

Reference analogue: the perf harnesses in test/legacy_test/benchmark.py;
kernel parity: phi/kernels/gpu/flash_attn_kernel.cu / flash_attn_grad_kernel.cu.
"""

from __future__ import annotations

import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.pallas_kernels.flash_attention import _flash


def xla_attn(q, k, v, scale):
    s_ = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                    k.astype(jnp.float32)) * scale
    n = q.shape[1]
    mask = jnp.tril(jnp.ones((n, n), bool))
    s_ = jnp.where(mask, s_, -1e30)
    p = jax.nn.softmax(s_, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p.astype(v.dtype), v)


def bench(fn, *args, iters=10):
    """Chained-scan differencing (see module docstring). ``fn`` returns
    either an array (fwd) or a (dq, dk, dv) tuple (grad); each iteration
    feeds an epsilon of the output back into the inputs so the scan
    cannot be parallelized or elided."""

    def chained(n):
        @jax.jit
        def run(args):
            def body(carry, _):
                out = fn(*carry)
                outs = out if isinstance(out, tuple) else (out,) * len(carry)
                new = tuple(a + o.astype(a.dtype) * 1e-6
                            for a, o in zip(carry, outs))
                return new, ()
            carry, _ = jax.lax.scan(body, tuple(args), None, length=n)
            return carry[0]

        _ = np.asarray(run(args)[0, 0])  # compile + warm
        best = float("inf")
        for _ in range(3):  # best-of-3: the transfer round trip is noisy
            t0 = time.perf_counter()
            _ = np.asarray(run(args)[0, 0])  # host transfer = real sync
            best = min(best, time.perf_counter() - t0)
        return best

    t1 = chained(1)
    tk = chained(iters + 1)
    return max(tk - t1, 1e-9) / iters


def main():
    d = 64
    for s, bh in ((1024, 192), (2048, 96), (4096, 32)):
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(bh, s, d), jnp.bfloat16)
        k = jnp.asarray(rng.randn(bh, s, d), jnp.bfloat16)
        v = jnp.asarray(rng.randn(bh, s, d), jnp.bfloat16)
        do = jnp.asarray(rng.randn(bh, s, d), jnp.bfloat16)
        scale = 1.0 / math.sqrt(d)

        # 512x512 blocks: the production default flash_attention() uses
        bq = bk = min(512, s)
        flash_f = jax.jit(lambda q, k, v: _flash(q, k, v, None, True, scale, bq, bk))
        xla_f = jax.jit(lambda q, k, v: xla_attn(q, k, v, scale))
        flash_g = jax.jit(jax.grad(
            lambda q, k, v: (_flash(q, k, v, None, True, scale, bq, bk) * do).sum(),
            argnums=(0, 1, 2)))
        xla_g = jax.jit(jax.grad(
            lambda q, k, v: (xla_attn(q, k, v, scale) * do).sum(), argnums=(0, 1, 2)))

        err = float(jnp.abs(flash_f(q, k, v).astype(jnp.float32)
                            - xla_f(q, k, v).astype(jnp.float32)).max())
        row = {
            "seq": s, "bh": bh, "head_dim": d, "max_abs_err": round(err, 4),
            "fwd_flash_ms": round(bench(flash_f, q, k, v) * 1e3, 2),
            "fwd_xla_ms": round(bench(xla_f, q, k, v) * 1e3, 2),
            "bwd_flash_ms": round(bench(flash_g, q, k, v) * 1e3, 2),
            "bwd_xla_ms": round(bench(xla_g, q, k, v) * 1e3, 2),
        }
        row["speedup_fwd"] = round(row["fwd_xla_ms"] / row["fwd_flash_ms"], 2)
        row["speedup_bwd"] = round(row["bwd_xla_ms"] / row["bwd_flash_ms"], 2)
        print(json.dumps(row))


if __name__ == "__main__":
    main()
