"""Summarize an xplane trace: top HLO ops by self time + category totals.

Usage: python benchmarks/xprof_top.py /tmp/trace_dir [N] [--json]

``--json`` prints one machine-readable JSON object (category totals +
top ops) so CI can diff category totals between runs instead of parsing
the human table.
"""
import argparse
import glob
import json
import sys
from collections import defaultdict


def _die(msg: str) -> "NoReturn":
    print(f"xprof_top: {msg}", file=sys.stderr)
    raise SystemExit(2)


def load(trace_dir):
    try:
        from xprof.convert import raw_to_tool_data as rtd
    except ImportError:
        _die("the 'xprof' package is not installed in this environment.\n"
             "  It ships with tensorboard-plugin-profile / the TPU "
             "tooling image;\n"
             "  install it (pip install xprof) or run this script where "
             "the profile\n  tooling is available. The raw trace itself "
             "is readable in TensorBoard.")
    pattern = f"{trace_dir}/plugins/profile/*/*.xplane.pb"
    f = glob.glob(pattern)
    if not f:
        _die(f"no xplane trace found under {pattern!r}.\n"
             "  Expected the directory passed to "
             "Profiler.start_device_trace(log_dir)\n"
             "  (or jax.profiler.start_trace) AFTER a stop_device_trace/"
             "stop_trace —\n  the .xplane.pb file is written on stop.")
    data, _ = rtd.xspace_to_tool_data(f, "hlo_stats", {})
    d = json.loads(data)
    cols = [c["id"] for c in d["cols"]]
    rows = [dict(zip(cols, [c["v"] for c in r["c"]])) for r in d["rows"]]
    return rows


def summarize(rows, n):
    total = sum(r["total_self_time"] for r in rows)
    cats = defaultdict(float)
    for r in rows:
        cats[r["category"]] += r["total_self_time"]
    rows = sorted(rows, key=lambda r: -r["total_self_time"])
    return {
        "total_self_time_ms": round(total / 1e3, 3),
        "categories": {c: round(t / 1e3, 3)
                       for c, t in sorted(cats.items(), key=lambda kv: -kv[1])},
        "top_ops": [
            {"self_time_ms": round(r["total_self_time"] / 1e3, 3),
             "pct": round(100 * r["total_self_time"] / total, 1) if total else 0.0,
             "occurrences": r["occurrences"],
             "category": r["category"],
             "expression": r["hlo_op_expression"][:110].replace("\n", " ")}
            for r in rows[:n]
        ],
    }


def main():
    ap = argparse.ArgumentParser(
        description="Top HLO ops / category totals from an xplane trace")
    ap.add_argument("trace_dir")
    ap.add_argument("n", nargs="?", type=int, default=25,
                    help="how many top ops to show (default 25)")
    ap.add_argument("--json", action="store_true",
                    help="print one JSON object (CI-diffable) instead of "
                         "the table")
    args = ap.parse_args()

    rows = load(args.trace_dir)
    if not rows:
        _die("the trace parsed but contains no HLO rows (empty capture? "
             "profile a window that executes device computations)")
    s = summarize(rows, args.n)

    if args.json:
        print(json.dumps(s, indent=1))
        return

    total = s["total_self_time_ms"]
    print(f"total device self time: {total:.2f} ms")
    print("\n-- by category --")
    for c, t in s["categories"].items():
        print(f"{c:<32}{t:>10.2f} ms {100*t/total if total else 0:>6.1f}%")
    print("\n-- top ops by self time --")
    for r in s["top_ops"]:
        print(f"{r['self_time_ms']:>9.2f} ms {r['pct']:>5.1f}%"
              f" x{r['occurrences']:<4} {r['category']:<22} {r['expression']}")


if __name__ == "__main__":
    main()
