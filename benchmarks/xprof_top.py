"""Summarize an xplane trace: top HLO ops by self time + category totals.

Usage: python benchmarks/xprof_top.py /tmp/trace_dir [N]
"""
import glob
import json
import sys
from collections import defaultdict

from xprof.convert import raw_to_tool_data as rtd


def load(trace_dir):
    f = glob.glob(f"{trace_dir}/plugins/profile/*/*.xplane.pb")
    data, _ = rtd.xspace_to_tool_data(f, "hlo_stats", {})
    d = json.loads(data)
    cols = [c["id"] for c in d["cols"]]
    rows = [dict(zip(cols, [c["v"] for c in r["c"]])) for r in d["rows"]]
    return rows


def main():
    trace_dir = sys.argv[1]
    n = int(sys.argv[2]) if len(sys.argv) > 2 else 25
    rows = load(trace_dir)
    total = sum(r["total_self_time"] for r in rows)
    cats = defaultdict(float)
    for r in rows:
        cats[r["category"]] += r["total_self_time"]
    print(f"total device self time: {total/1e3:.2f} ms")
    print("\n-- by category --")
    for c, t in sorted(cats.items(), key=lambda kv: -kv[1]):
        print(f"{c:<32}{t/1e3:>10.2f} ms {100*t/total:>6.1f}%")
    print("\n-- top ops by self time --")
    rows.sort(key=lambda r: -r["total_self_time"])
    for r in rows[:n]:
        expr = r["hlo_op_expression"][:110].replace("\n", " ")
        print(f"{r['total_self_time']/1e3:>9.2f} ms {100*r['total_self_time']/total:>5.1f}%"
              f" x{r['occurrences']:<4} {r['category']:<22} {expr}")


if __name__ == "__main__":
    main()
