"""Summarize an xplane trace: top HLO ops by self time + category totals.

Usage: python benchmarks/xprof_top.py /tmp/trace_dir [N] [--json]

``--json`` prints one machine-readable JSON object (category totals +
top ops) so CI can diff category totals between runs instead of parsing
the human table. The JSON also carries the roofline columns from
``paddle_tpu.observability.perf``: the device peak table in force
(env-overridable via PADDLE_TPU_PEAK_FLOPS / PADDLE_TPU_PEAK_HBM_GBPS)
and, for every op row whose hlo_stats carry flop/byte counts, the
arithmetic intensity + compute-vs-bandwidth-bound classification —
the same classifier the serving ledger publishes, so a trace summary
and ``observability.snapshot()["perf"]`` speak one vocabulary.
"""
import argparse
import glob
import json
import sys
from collections import defaultdict


def _die(msg: str) -> "NoReturn":
    print(f"xprof_top: {msg}", file=sys.stderr)
    raise SystemExit(2)


def load(trace_dir):
    try:
        from xprof.convert import raw_to_tool_data as rtd
    except ImportError:
        _die("the 'xprof' package is not installed in this environment.\n"
             "  It ships with tensorboard-plugin-profile / the TPU "
             "tooling image;\n"
             "  install it (pip install xprof) or run this script where "
             "the profile\n  tooling is available. The raw trace itself "
             "is readable in TensorBoard.")
    pattern = f"{trace_dir}/plugins/profile/*/*.xplane.pb"
    f = glob.glob(pattern)
    if not f:
        _die(f"no xplane trace found under {pattern!r}.\n"
             "  Expected the directory passed to "
             "Profiler.start_device_trace(log_dir)\n"
             "  (or jax.profiler.start_trace) AFTER a stop_device_trace/"
             "stop_trace —\n  the .xplane.pb file is written on stop.")
    data, _ = rtd.xspace_to_tool_data(f, "hlo_stats", {})
    d = json.loads(data)
    cols = [c["id"] for c in d["cols"]]
    rows = [dict(zip(cols, [c["v"] for c in r["c"]])) for r in d["rows"]]
    return rows


def _peaks():
    """The perf module's peak table (None-peaked on unknown devices);
    the script stays usable without the package on path."""
    try:
        from paddle_tpu.observability import perf

        return perf.peak_specs()
    except Exception:
        return {"device_kind": None, "peak_flops_per_s": None,
                "peak_hbm_gbps": None,
                "machine_balance_flops_per_byte": None,
                "source": "unavailable (paddle_tpu not importable)"}


def _first(row, *keys):
    for k in keys:
        v = row.get(k)
        if isinstance(v, (int, float)) and v > 0:
            return float(v)
    return None


def _roofline_cols(row, peaks):
    """Intensity + roofline class for one hlo_stats row, from whichever
    flop/byte columns this xprof version exposes; {} when the trace
    carries neither (honest absence, never invented numbers)."""
    flops = _first(row, "model_flops", "flops", "measured_flops")
    nbytes = _first(row, "bytes_accessed", "memory_bytes_accessed",
                    "bytes accessed")
    self_us = _first(row, "total_self_time")
    out = {}
    if flops is not None:
        out["flops"] = flops
    if nbytes is not None:
        out["bytes_accessed"] = nbytes
    if flops is not None and nbytes is not None:
        out["arithmetic_intensity"] = round(flops / nbytes, 3)
        balance = peaks.get("machine_balance_flops_per_byte")
        if balance is not None:
            out["roofline"] = ("compute-bound"
                               if flops / nbytes >= balance
                               else "bandwidth-bound")
    if self_us:
        if flops is not None:
            out["achieved_gflops_per_s"] = round(flops / (self_us * 1e3), 2)
            pf = peaks.get("peak_flops_per_s")
            if pf:
                out["mfu"] = round(flops / (self_us * 1e-6) / pf, 4)
        if nbytes is not None:
            out["achieved_gbps"] = round(nbytes / (self_us * 1e3), 2)
            pb = peaks.get("peak_hbm_gbps")
            if pb:
                out["hbm_bw_util"] = round(
                    nbytes / (self_us * 1e-6) / (pb * 1e9), 4)
    return out


def summarize(rows, n):
    total = sum(r["total_self_time"] for r in rows)
    cats = defaultdict(float)
    for r in rows:
        cats[r["category"]] += r["total_self_time"]
    rows = sorted(rows, key=lambda r: -r["total_self_time"])
    peaks = _peaks()
    return {
        "total_self_time_ms": round(total / 1e3, 3),
        "peaks": peaks,
        "categories": {c: round(t / 1e3, 3)
                       for c, t in sorted(cats.items(), key=lambda kv: -kv[1])},
        "top_ops": [
            {"self_time_ms": round(r["total_self_time"] / 1e3, 3),
             "pct": round(100 * r["total_self_time"] / total, 1) if total else 0.0,
             "occurrences": r["occurrences"],
             "category": r["category"],
             **_roofline_cols(r, peaks),
             "expression": r["hlo_op_expression"][:110].replace("\n", " ")}
            for r in rows[:n]
        ],
    }


def main():
    ap = argparse.ArgumentParser(
        description="Top HLO ops / category totals from an xplane trace")
    ap.add_argument("trace_dir")
    ap.add_argument("n", nargs="?", type=int, default=25,
                    help="how many top ops to show (default 25)")
    ap.add_argument("--json", action="store_true",
                    help="print one JSON object (CI-diffable) instead of "
                         "the table, incl. the perf roofline columns")
    args = ap.parse_args()

    rows = load(args.trace_dir)
    if not rows:
        _die("the trace parsed but contains no HLO rows (empty capture? "
             "profile a window that executes device computations)")
    s = summarize(rows, args.n)

    if args.json:
        print(json.dumps(s, indent=1))
        return

    total = s["total_self_time_ms"]
    print(f"total device self time: {total:.2f} ms")
    print("\n-- by category --")
    for c, t in s["categories"].items():
        print(f"{c:<32}{t:>10.2f} ms {100*t/total if total else 0:>6.1f}%")
    print("\n-- top ops by self time --")
    for r in s["top_ops"]:
        roof = f" [{r['roofline']}]" if "roofline" in r else ""
        print(f"{r['self_time_ms']:>9.2f} ms {r['pct']:>5.1f}%"
              f" x{r['occurrences']:<4} {r['category']:<22}"
              f" {r['expression']}{roof}")


if __name__ == "__main__":
    main()
