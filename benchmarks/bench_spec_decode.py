"""Speculative-decoding lane: A/B spec vs plain paged decode.

The acceptance workload for the serving engine's draft+verify lane:
identical request sets decoded through (a) the plain paged engine and
(b) the speculative engine at k ∈ {2, 4, 8}, across occupancy levels
(1, half, full slots). Two draft configurations bound the answer:

- ``coupled``: the target's tail layers are zeroed to exact identities
  and the draft is ``generation.truncated_draft`` of the live prefix —
  functionally ONE model in two sizes, so the accept rate is
  deterministically 1.0 and the measured speedup is the MECHANICAL
  ceiling of the lane (draft cost + verify cost vs per-token steps) at
  each k. Real models land between this and the floor in proportion to
  their accept rate — which is why the artifact reports accept rate
  next to every tok/s number.
- ``adversarial``: an independent random draft (accept rate ~0) — the
  overhead floor: every round pays k draft forwards + one k+1-wide
  verify and advances one token.

The bench asserts while it measures:
- every speculative request bit-matches its plain-engine twin (the
  coupling contract: speculation NEVER changes output);
- zero spec_draft/spec_verify compiles in the measured passes (warmup
  compiled them; accept-length patterns are data);
- best coupled config reaches >= 1.3x plain paged decode tok/s.

Artifact: ``benchmarks/bench_spec_decode.json`` — per (k, occupancy,
draft) tok/s + accept rates + verdicts; ``tests/run_shards.py`` folds it
into ``telemetry_lane.json`` as ``spec_decode_bench``. CPU numbers size
the win on the dev box (decode here is weight-streaming/dispatch-bound,
the same regime that makes spec decode pay on chip); the chip lane
reruns this for real numbers.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import generation, serving
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import recompile

HERE = os.path.dirname(os.path.abspath(__file__))

MAX_SLOTS = 4
MAX_LEN = 128
MAX_NEW = 48
PROMPT_LEN = 12
KS = (2, 4, 8)
OCCUPANCIES = (1, 2, 4)  # concurrent requests per pass

# weight-streaming-bound decode (the serving regime — see
# bench_serving.py): wide enough that a [B, q] forward's wall time is
# dominated by streaming the weights, so a k+1-wide verify costs about
# one step and the draft's layer ratio is the whole draft cost
MODEL_KW = dict(hidden_size=512, intermediate_size=1024,
                num_hidden_layers=6, num_attention_heads=8,
                num_key_value_heads=4, vocab_size=4096,
                max_position_embeddings=MAX_LEN)
DRAFT_LAYERS = 1


def zero_tail_layers(model, keep: int):
    """Zero the attn output / MLP down projections of layers >= keep:
    pre-norm residual blocks become exact identities, so the target IS
    its first ``keep`` layers (deterministic accept-rate-1 coupling)."""
    for name, p in model.state_dict().items():
        for i in range(keep, model.config.num_hidden_layers):
            if (f"layers.{i}.self_attn.o_proj" in name
                    or f"layers.{i}.mlp.down_proj" in name):
                p._data = p._data * 0.0


def run_requests(engine, prompts):
    """Submit all prompts, drive to idle, return (requests, wall_s)."""
    t0 = time.perf_counter()
    reqs = [engine.submit(p, max_new_tokens=MAX_NEW) for p in prompts]
    engine.run_until_idle()
    return reqs, time.perf_counter() - t0


def bench_engine(make_engine, prompt_sets, entries):
    """Warmup once (compiles), then one measured pass per occupancy
    level; returns per-occupancy {tok_s, accept_rate} plus compile
    deltas for the named recompile entries over the measured passes."""
    eng = make_engine()
    run_requests(eng, prompt_sets[-1])  # warmup at full occupancy
    before = {n: recompile.entry_stats().get(n, {"compiles": 0,
                                                 "retraces": 0})
              for n in entries}
    out = {}
    outputs = {}
    for prompts in prompt_sets:
        occ = len(prompts)
        best = float("inf")
        reqs = None
        for _ in range(2):
            r, wall = run_requests(eng, prompts)
            if wall < best:
                best, reqs = wall, r
        spec = eng.stats()["spec"]
        out[occ] = {
            "tok_s": round(occ * MAX_NEW / best, 1),
            "wall_s": round(best, 3),
            "accept_rate": (round(spec["accept_rate"], 3)
                            if spec.get("accept_rate") is not None
                            else None),
        }
        outputs[occ] = [r.result(timeout=5) for r in reqs]
    after = {n: recompile.entry_stats().get(n, {"compiles": 0,
                                                "retraces": 0})
             for n in entries}
    compiles = {n: after[n]["compiles"] - before[n]["compiles"]
                for n in entries}
    retraces = {n: after[n]["retraces"] - before[n]["retraces"]
                for n in entries}
    return out, outputs, compiles, retraces


def main():
    paddle.seed(0)
    cfg = LlamaConfig.tiny(**MODEL_KW)
    target = LlamaForCausalLM(cfg)
    zero_tail_layers(target, DRAFT_LAYERS)
    draft = generation.truncated_draft(target, DRAFT_LAYERS)
    paddle.seed(77)
    adversarial = LlamaForCausalLM(LlamaConfig.tiny(
        **{**MODEL_KW, "num_hidden_layers": DRAFT_LAYERS}))

    rng = np.random.RandomState(42)
    prompt_sets = [
        [rng.randint(1, cfg.vocab_size, PROMPT_LEN).astype(np.int32)
         for _ in range(occ)]
        for occ in OCCUPANCIES]

    def eng_kw():
        return dict(max_slots=MAX_SLOTS, max_len=MAX_LEN,
                    max_queue_depth=32)

    result = {
        "bench": "spec_decode_vs_plain_paged",
        "platform": jax.default_backend(),
        "model": {"family": "llama", **MODEL_KW,
                  "draft_layers": DRAFT_LAYERS},
        "max_new_tokens": MAX_NEW,
        "occupancies": list(OCCUPANCIES),
    }

    plain, plain_out, _, plain_retr = bench_engine(
        lambda: serving.ServingEngine(target, **eng_kw()),
        prompt_sets, ("serving.step",))
    result["plain"] = plain

    spec_entries = ("serving.spec_draft", "serving.spec_verify")
    parity_ok = True
    zero_compiles = True
    for k in KS:
        spec, spec_out, compiles, retraces = bench_engine(
            lambda k=k: serving.ServingEngine(
                target, draft_model=draft, spec_k=k, **eng_kw()),
            prompt_sets, spec_entries)
        for occ in OCCUPANCIES:
            if spec_out[occ] != plain_out[occ]:
                parity_ok = False
            spec[occ]["speedup_vs_plain"] = round(
                spec[occ]["tok_s"] / plain[occ]["tok_s"], 2)
        if any(compiles.values()) or any(retraces.values()):
            zero_compiles = False
        result[f"spec_k{k}_coupled"] = {
            "by_occupancy": spec,
            "measured_pass_compiles": compiles,
            "measured_pass_retraces": retraces,
        }

    # adversarial draft: the overhead floor, one config is enough
    adv, adv_out, _, _ = bench_engine(
        lambda: serving.ServingEngine(
            target, draft_model=adversarial, spec_k=4, **eng_kw()),
        prompt_sets[:1], spec_entries)
    if adv_out[OCCUPANCIES[0]] != plain_out[OCCUPANCIES[0]]:
        parity_ok = False
    adv[OCCUPANCIES[0]]["speedup_vs_plain"] = round(
        adv[OCCUPANCIES[0]]["tok_s"] / plain[OCCUPANCIES[0]]["tok_s"], 2)
    result["spec_k4_adversarial"] = adv

    best = max(
        result[f"spec_k{k}_coupled"]["by_occupancy"][occ]
        ["speedup_vs_plain"]
        for k in KS for occ in OCCUPANCIES)
    best_rate = max(
        result[f"spec_k{k}_coupled"]["by_occupancy"][occ]["accept_rate"]
        for k in KS for occ in OCCUPANCIES)
    result["best_speedup"] = best
    result["best_config_accept_rate"] = best_rate
    result["per_request_parity"] = bool(parity_ok)
    result["zero_spec_compiles_measured"] = bool(zero_compiles)
    result["acceptance_1p3x"] = bool(best >= 1.3)

    path = os.path.join(HERE, "bench_spec_decode.json")
    with open(path, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps(result, indent=1))
    print(f"[bench_spec_decode] artifact -> {path}")

    ok = parity_ok and zero_compiles and best >= 1.3
    if not ok:
        print("[bench_spec_decode] ACCEPTANCE FAILED", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
