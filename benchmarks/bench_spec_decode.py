"""Speculative-decoding lane: A/B spec vs plain paged decode.

The acceptance workload for the serving engine's draft+verify lane:
identical request sets decoded through (a) the plain paged engine and
(b) the speculative engine at k ∈ {2, 4, 8}, across occupancy levels
(1, half, full slots). Two draft configurations bound the answer:

- ``coupled``: the target's tail layers are zeroed to exact identities
  and the draft is ``generation.truncated_draft`` of the live prefix —
  functionally ONE model in two sizes, so the accept rate is
  deterministically 1.0 and the measured speedup is the MECHANICAL
  ceiling of the lane (draft cost + verify cost vs per-token steps) at
  each k. Real models land between this and the floor in proportion to
  their accept rate — which is why the artifact reports accept rate
  next to every tok/s number.
- ``adversarial``: an independent random draft (accept rate ~0) — the
  overhead floor: every round pays k draft forwards + one k+1-wide
  verify and advances one token.

Tree lanes ride the same harness at EQUAL drafted-token budget vs the
chain: ``spec_tree=[1,1,1,1]`` (the chain as a degenerate tree — the
mechanical-overhead ceiling pin vs ``spec_k=4``) and ``spec_tree=[2,2]``
vs ``spec_k=6`` (same 6-token budget; the branching payoff is measured
at the adversarial floor where the 2-level draft halves round cost).

The bench asserts while it measures:
- every speculative request — chain AND tree — bit-matches its
  plain-engine twin (the coupling contract: speculation NEVER changes
  output);
- zero spec_draft/spec_verify compiles in the measured passes (warmup
  compiled them; accept-length patterns are data);
- best coupled config reaches >= 1.3x plain paged decode tok/s;
- the tree beats the chain at equal drafted budget on the adversarial
  floor (``[2,2]`` vs ``spec_k=6``: two level forwards replace six
  serial draft steps per round, so the round is cheaper where
  acceptance is draft-quality-bound — the lane branching exists for).

The coupled accept-1.0 ceiling is where a branching tree CANNOT beat a
chain at equal budget (chain k=6 commits 7 tokens/round; tree [2,2]
commits 3), so the coupled lanes are reported, not gated. Even the
degenerate ``[1,1,1,1]`` twin pays a structural CPU-box tax vs
``spec_k=4``: the tree draft runs D level forwards PLUS one write-only
full-width forward (leaf KV), D+1 dispatches vs the chain's D, and
re-feeds the whole tree-so-far each level (the kernel's in-bundle
ancestor mask is square). On chip those extra dispatches are
bandwidth-amortized; on this dispatch-bound box the ratio measures
~D/(D+1). That ratio is pinned in ``perf_baseline.json`` as a
mechanical-overhead REGRESSION guard, not a >=1 claim.

Artifact: ``benchmarks/bench_spec_decode.json`` — per (k, occupancy,
draft) tok/s + accept rates + verdicts; ``tests/run_shards.py`` folds it
into ``telemetry_lane.json`` as ``spec_decode_bench``. CPU numbers size
the win on the dev box (decode here is weight-streaming/dispatch-bound,
the same regime that makes spec decode pay on chip); the chip lane
reruns this for real numbers.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import generation, serving
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import recompile

HERE = os.path.dirname(os.path.abspath(__file__))

MAX_SLOTS = 4
MAX_LEN = 128
MAX_NEW = 48
PROMPT_LEN = 12
KS = (2, 4, 8)
OCCUPANCIES = (1, 2, 4)  # concurrent requests per pass

# weight-streaming-bound decode (the serving regime — see
# bench_serving.py): wide enough that a [B, q] forward's wall time is
# dominated by streaming the weights, so a k+1-wide verify costs about
# one step and the draft's layer ratio is the whole draft cost
MODEL_KW = dict(hidden_size=512, intermediate_size=1024,
                num_hidden_layers=6, num_attention_heads=8,
                num_key_value_heads=4, vocab_size=4096,
                max_position_embeddings=MAX_LEN)
DRAFT_LAYERS = 1


def zero_tail_layers(model, keep: int):
    """Zero the attn output / MLP down projections of layers >= keep:
    pre-norm residual blocks become exact identities, so the target IS
    its first ``keep`` layers (deterministic accept-rate-1 coupling)."""
    for name, p in model.state_dict().items():
        for i in range(keep, model.config.num_hidden_layers):
            if (f"layers.{i}.self_attn.o_proj" in name
                    or f"layers.{i}.mlp.down_proj" in name):
                p._data = p._data * 0.0


def run_requests(engine, prompts):
    """Submit all prompts, drive to idle, return (requests, wall_s)."""
    t0 = time.perf_counter()
    reqs = [engine.submit(p, max_new_tokens=MAX_NEW) for p in prompts]
    engine.run_until_idle()
    return reqs, time.perf_counter() - t0


def bench_engine(make_engine, prompt_sets, entries):
    """Warmup once (compiles), then one measured pass per occupancy
    level; returns per-occupancy {tok_s, accept_rate} plus compile
    deltas for the named recompile entries over the measured passes."""
    eng = make_engine()
    run_requests(eng, prompt_sets[-1])  # warmup at full occupancy
    before = {n: recompile.entry_stats().get(n, {"compiles": 0,
                                                 "retraces": 0})
              for n in entries}
    out = {}
    outputs = {}
    for prompts in prompt_sets:
        occ = len(prompts)
        best = float("inf")
        reqs = None
        for _ in range(2):
            r, wall = run_requests(eng, prompts)
            if wall < best:
                best, reqs = wall, r
        spec = eng.stats()["spec"]
        out[occ] = {
            "tok_s": round(occ * MAX_NEW / best, 1),
            "wall_s": round(best, 3),
            "accept_rate": (round(spec["accept_rate"], 3)
                            if spec.get("accept_rate") is not None
                            else None),
        }
        outputs[occ] = [r.result(timeout=5) for r in reqs]
    after = {n: recompile.entry_stats().get(n, {"compiles": 0,
                                                "retraces": 0})
             for n in entries}
    compiles = {n: after[n]["compiles"] - before[n]["compiles"]
                for n in entries}
    retraces = {n: after[n]["retraces"] - before[n]["retraces"]
                for n in entries}
    return out, outputs, compiles, retraces


def main():
    paddle.seed(0)
    cfg = LlamaConfig.tiny(**MODEL_KW)
    target = LlamaForCausalLM(cfg)
    zero_tail_layers(target, DRAFT_LAYERS)
    draft = generation.truncated_draft(target, DRAFT_LAYERS)
    paddle.seed(77)
    adversarial = LlamaForCausalLM(LlamaConfig.tiny(
        **{**MODEL_KW, "num_hidden_layers": DRAFT_LAYERS}))

    rng = np.random.RandomState(42)
    prompt_sets = [
        [rng.randint(1, cfg.vocab_size, PROMPT_LEN).astype(np.int32)
         for _ in range(occ)]
        for occ in OCCUPANCIES]

    def eng_kw():
        return dict(max_slots=MAX_SLOTS, max_len=MAX_LEN,
                    max_queue_depth=32)

    result = {
        "bench": "spec_decode_vs_plain_paged",
        "platform": jax.default_backend(),
        "model": {"family": "llama", **MODEL_KW,
                  "draft_layers": DRAFT_LAYERS},
        "max_new_tokens": MAX_NEW,
        "occupancies": list(OCCUPANCIES),
    }

    plain, plain_out, _, plain_retr = bench_engine(
        lambda: serving.ServingEngine(target, **eng_kw()),
        prompt_sets, ("serving.step",))
    result["plain"] = plain

    spec_entries = ("serving.spec_draft", "serving.spec_verify")
    parity_ok = True
    zero_compiles = True
    for k in KS:
        spec, spec_out, compiles, retraces = bench_engine(
            lambda k=k: serving.ServingEngine(
                target, draft_model=draft, spec_k=k, **eng_kw()),
            prompt_sets, spec_entries)
        for occ in OCCUPANCIES:
            if spec_out[occ] != plain_out[occ]:
                parity_ok = False
            spec[occ]["speedup_vs_plain"] = round(
                spec[occ]["tok_s"] / plain[occ]["tok_s"], 2)
        if any(compiles.values()) or any(retraces.values()):
            zero_compiles = False
        result[f"spec_k{k}_coupled"] = {
            "by_occupancy": spec,
            "measured_pass_compiles": compiles,
            "measured_pass_retraces": retraces,
        }

    # adversarial draft: the overhead floor, one config is enough
    adv, adv_out, _, _ = bench_engine(
        lambda: serving.ServingEngine(
            target, draft_model=adversarial, spec_k=4, **eng_kw()),
        prompt_sets[:1], spec_entries)
    if adv_out[OCCUPANCIES[0]] != plain_out[OCCUPANCIES[0]]:
        parity_ok = False
    adv[OCCUPANCIES[0]]["speedup_vs_plain"] = round(
        adv[OCCUPANCIES[0]]["tok_s"] / plain[OCCUPANCIES[0]]["tok_s"], 2)
    result["spec_k4_adversarial"] = adv

    # --- tree lanes: tree vs chain at EQUAL drafted-token budget -----------
    # (a) [1,1,1,1] is the chain expressed as a degenerate tree — same
    #     4-token budget, same serial draft depth, same accepts as
    #     spec_k=4 — so its coupled accept-1.0 ratio isolates the tree
    #     lane's mechanical overhead (ancestor-mask operand, path-move
    #     commit, per-branch folded keys) and must not lose to the
    #     chain. This is the tree>=chain equal-budget ceiling pin.
    # (b) [2,2] drafts the SAME 6-token budget as spec_k=6 in 2 level
    #     forwards instead of 6 serial ones. A branching tree spends
    #     its budget on siblings, not depth, so on a deterministic
    #     accept-1.0 workload its ceiling sits BELOW the chain's by
    #     construction (3 commits/round vs 7) — reported honestly, not
    #     gated. The branching payoff shows at the adversarial floor:
    #     rounds are ~half the forwards, so tok/s at accept~0 is
    #     strictly better, and real workloads interpolate toward it as
    #     sibling hedges rescue rejected chains.
    tree_parity_ok = True
    tree_id, tree_id_out, id_compiles, id_retraces = bench_engine(
        lambda: serving.ServingEngine(
            target, draft_model=draft, spec_tree=[1, 1, 1, 1], **eng_kw()),
        prompt_sets, spec_entries)
    if any(id_compiles.values()) or any(id_retraces.values()):
        zero_compiles = False
    chain4 = result["spec_k4_coupled"]["by_occupancy"]
    for occ in OCCUPANCIES:
        if tree_id_out[occ] != plain_out[occ]:
            tree_parity_ok = False
        tree_id[occ]["tok_s_ratio_vs_chain"] = round(
            tree_id[occ]["tok_s"] / chain4[occ]["tok_s"], 3)
    result["spec_tree_1111_coupled"] = {
        "by_occupancy": tree_id, "chain_twin": "spec_k4_coupled",
        "measured_pass_compiles": id_compiles,
        "measured_pass_retraces": id_retraces,
    }

    occ1 = OCCUPANCIES[0]
    chain6, chain6_out, _, _ = bench_engine(
        lambda: serving.ServingEngine(
            target, draft_model=draft, spec_k=6, **eng_kw()),
        prompt_sets[:1], spec_entries)
    tree22, tree22_out, t22_compiles, t22_retraces = bench_engine(
        lambda: serving.ServingEngine(
            target, draft_model=draft, spec_tree=[2, 2], **eng_kw()),
        prompt_sets[:1], spec_entries)
    chain6_adv, chain6_adv_out, _, _ = bench_engine(
        lambda: serving.ServingEngine(
            target, draft_model=adversarial, spec_k=6, **eng_kw()),
        prompt_sets[:1], spec_entries)
    tree22_adv, tree22_adv_out, _, _ = bench_engine(
        lambda: serving.ServingEngine(
            target, draft_model=adversarial, spec_tree=[2, 2], **eng_kw()),
        prompt_sets[:1], spec_entries)
    for out in (chain6_out, tree22_out, chain6_adv_out, tree22_adv_out):
        if out[occ1] != plain_out[occ1]:
            tree_parity_ok = False
    if any(t22_compiles.values()) or any(t22_retraces.values()):
        zero_compiles = False
    result["equal_budget_6"] = {
        "chain_k6_coupled": chain6[occ1],
        "tree_22_coupled": dict(
            tree22[occ1], tok_s_ratio_vs_chain=round(
                tree22[occ1]["tok_s"] / chain6[occ1]["tok_s"], 3)),
        "chain_k6_adversarial": chain6_adv[occ1],
        "tree_22_adversarial": dict(
            tree22_adv[occ1], tok_s_ratio_vs_chain=round(
                tree22_adv[occ1]["tok_s"] / chain6_adv[occ1]["tok_s"], 3)),
    }

    tree_ratio = max(tree_id[occ]["tok_s_ratio_vs_chain"]
                     for occ in OCCUPANCIES)
    floor_ratio = result["equal_budget_6"]["tree_22_adversarial"][
        "tok_s_ratio_vs_chain"]
    result["spec_tree"] = {
        # degenerate-tree twin vs spec_k=4, coupled: the mechanical-
        # overhead pin (D+1 draft dispatches vs D + whole-tree re-feed
        # ~= D/(D+1) on this dispatch-bound box; bandwidth-amortized on
        # chip). A regression guard via perf_baseline.json, NOT a >=1
        # claim — see module docstring.
        "tok_s_ratio_vs_chain": tree_ratio,
        "adversarial_floor_ratio_vs_chain": floor_ratio,
        "parity": 1.0 if tree_parity_ok else 0.0,
    }
    # equal-budget verdict on the lane branching exists for: where
    # acceptance is draft-quality-bound, two [2,2] level forwards must
    # beat six serial chain draft steps per round
    result["tree_ge_chain_equal_budget"] = bool(floor_ratio >= 1.0)

    best = max(
        result[f"spec_k{k}_coupled"]["by_occupancy"][occ]
        ["speedup_vs_plain"]
        for k in KS for occ in OCCUPANCIES)
    best_rate = max(
        result[f"spec_k{k}_coupled"]["by_occupancy"][occ]["accept_rate"]
        for k in KS for occ in OCCUPANCIES)
    result["best_speedup"] = best
    result["best_config_accept_rate"] = best_rate
    result["per_request_parity"] = bool(parity_ok and tree_parity_ok)
    result["zero_spec_compiles_measured"] = bool(zero_compiles)
    result["acceptance_1p3x"] = bool(best >= 1.3)

    path = os.path.join(HERE, "bench_spec_decode.json")
    with open(path, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps(result, indent=1))
    print(f"[bench_spec_decode] artifact -> {path}")

    ok = (parity_ok and tree_parity_ok and zero_compiles and best >= 1.3
          and result["tree_ge_chain_equal_budget"])
    if not ok:
        print("[bench_spec_decode] ACCEPTANCE FAILED", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
