"""Eager per-op dispatch latency microbench.

Reference analogue: test/cpp/eager/performance_tests/benchmark_eager_cuda.cc
(per-op dispatch overhead is the eager-mode bottleneck, SURVEY §7.3 #1).

Measures ops/sec through the full dispatch stack (AMP hook, tape,
autograd) for small tensors, where Python/tracing overhead dominates.
Prints one JSON line. Run on CPU for stable numbers:
  JAX_PLATFORMS=cpu python benchmarks/bench_eager_dispatch.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def rate(f, n=300):
    f()  # warm (compile/cache)
    f()
    t0 = time.perf_counter()
    for _ in range(n):
        f()
    dt = time.perf_counter() - t0
    return n / dt


def main():
    import paddle_tpu as paddle

    x = paddle.to_tensor(np.random.randn(16, 16).astype(np.float32))
    y = paddle.to_tensor(np.random.randn(16, 16).astype(np.float32))
    w = paddle.to_tensor(np.random.randn(128, 128).astype(np.float32))
    a = paddle.to_tensor(np.random.randn(8, 128).astype(np.float32))
    b = paddle.to_tensor(np.zeros(128, np.float32))

    results = {
        "add_fwd_ops_per_sec": rate(lambda: x + y),
        "matmul_fwd_ops_per_sec": rate(lambda: a.matmul(w)),
        "mlp3_fwd_ops_per_sec": rate(lambda: paddle.nn.functional.relu(a.matmul(w) + b)),
    }

    def train_add():
        xg = paddle.to_tensor(np.random.randn(16, 16).astype(np.float32),
                              stop_gradient=False)
        (xg + y).sum().backward()

    def train_mlp():
        wg = paddle.to_tensor(np.random.randn(128, 128).astype(np.float32),
                              stop_gradient=False)
        paddle.nn.functional.relu(a.matmul(wg) + b).sum().backward()

    results["add_fwd_bwd_per_sec"] = rate(train_add, n=100)
    results["mlp3_fwd_bwd_per_sec"] = rate(train_mlp, n=100)

    import jax

    print(json.dumps({
        "metric": "eager_dispatch",
        "backend": jax.default_backend(),
        **{k: round(v, 1) for k, v in results.items()},
    }))


if __name__ == "__main__":
    main()
