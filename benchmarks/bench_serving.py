"""Serving throughput+latency lane: continuous batching vs sequential.

The acceptance workload for paddle_tpu/serving/: 12 requests with
STAGGERED arrivals (deterministic arrival schedule in engine steps),
mixed prompt lengths across two prefill buckets and mixed greedy/sampled
params, decoded two ways:

- ``serving``:    one ``ServingEngine`` (slot pool, bucketed prefill,
                  ONE jitted decode step for the whole pool) — requests
                  are injected mid-flight per the arrival schedule.
- ``sequential``: the same 12 requests as back-to-back
                  ``generation.generate`` calls in arrival order (the
                  pre-serving status quo: one request, one (1, S, N)
                  program, whole-batch lockstep).

Both lanes run the full workload once as WARMUP (all executables
compile) and are measured on the second pass, so the comparison is
steady-state throughput, not compile time. The bench asserts the
engine's three acceptance properties while it measures:

- per-request outputs match ``generate()`` with the same seed/params;
- the recompile monitor records EXACTLY one ``serving.step`` compile
  and zero retraces across the measured pass (tracing ENABLED);
- aggregate serving tok/s > sequential tok/s;
- request-lifecycle tracing (default-on) costs <2% tok/s: a
  tracing-off serving pass rides in the same alternating rotation and
  the A/B lands in the artifact's ``tracing`` block;
- perf capture (default-on cost/roofline ledger) costs <2% tok/s: a
  perf-off pass rides the same rotation into the ``perf_capture``
  block (capture is compile-time + one entry-exit clock read — the
  measured pass pays only the clock read);
- SPECULATIVE on/off rides the same rotation: a draft-model engine
  (independent random draft — the adversarial accept-rate floor, so
  this is a pure correctness/overhead lane; ``bench_spec_decode.py``
  owns the speedup acceptance) must produce BIT-IDENTICAL outputs and
  zero spec_draft/spec_verify compiles across the measured passes —
  both asserted in the exit code.

Artifact: ``benchmarks/bench_serving.json`` — tok/s all lanes, speedup,
mean/p95 TTFT + TPOT, mean slot occupancy, parity/compile verdicts,
tracing overhead A/B.
``tests/run_shards.py`` folds it into ``telemetry_lane.json`` as the
``serving_bench`` block. CPU numbers here size the continuous-batching
win on the dev box; the chip lane reruns this on TPU for real numbers.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import generation, serving
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import perf, recompile, tracing

HERE = os.path.dirname(os.path.abspath(__file__))

# the staggered 12-request workload: (arrival_step, prompt_len, params).
# arrival_step is in ENGINE ITERATIONS — request k is submitted once the
# engine has run that many decode steps, so later requests land while
# earlier ones are mid-decode (the continuous-batching case, not a
# one-shot batch).
WORKLOAD = [
    (0, 5, dict(max_new_tokens=48)),
    (0, 9, dict(max_new_tokens=40, do_sample=True, temperature=0.8,
                top_k=8, seed=1)),
    (0, 14, dict(max_new_tokens=56)),
    # top-p WITHOUT top-k: the one request that exercises the sampler's
    # exact full-sort fallback (see generation._NUCLEUS_BOUND)
    (0, 26, dict(max_new_tokens=32, do_sample=True, top_p=0.9, seed=2)),
    (2, 7, dict(max_new_tokens=48)),
    (4, 11, dict(max_new_tokens=24, do_sample=True, temperature=1.1,
                 top_k=12, seed=3)),
    (6, 19, dict(max_new_tokens=40)),
    (8, 4, dict(max_new_tokens=16)),
    (10, 30, dict(max_new_tokens=48, do_sample=True, top_k=64, top_p=0.95,
                  seed=4)),
    (12, 6, dict(max_new_tokens=32)),
    (14, 13, dict(max_new_tokens=24, do_sample=True, temperature=0.9,
                  top_k=6, seed=5)),
    (16, 8, dict(max_new_tokens=40)),
]
MAX_SLOTS = 6
MAX_LEN = 96


# Big enough that a decode step is weight-streaming-bound (the serving
# regime: a B-row step streams the weights ONCE for B streams, which is
# the whole continuous-batching win) — at toy widths the scan-mode
# sequential program wins on pure dispatch amortization instead.
MODEL_KW = dict(hidden_size=512, intermediate_size=1024,
                num_hidden_layers=4, num_attention_heads=8,
                num_key_value_heads=4, vocab_size=4096)


def make_workload(cfg):
    rng = np.random.RandomState(42)
    return [(step, rng.randint(1, cfg.vocab_size, n).astype(np.int32), p)
            for step, n, p in WORKLOAD]


def run_serving(engine, workload):
    """Drive the engine synchronously, injecting each request at its
    scheduled iteration; returns (requests, wall_s)."""
    pending = list(workload)
    reqs = []
    t0 = time.perf_counter()
    steps = 0
    while pending or engine.scheduler.depth or engine.busy_slots():
        while pending and pending[0][0] <= steps:
            _, prompt, params = pending.pop(0)
            reqs.append(engine.submit(prompt, **params))
        if not engine.step() and not pending:
            break
        steps += 1
    return reqs, time.perf_counter() - t0


def run_sequential(model, workload):
    """The status quo: one generate() per request, arrival order.
    Returns (outputs, wall_s)."""
    outs = []
    t0 = time.perf_counter()
    for _, prompt, params in workload:
        out = generation.generate(model, prompt[None], **params)
        outs.append(np.asarray(out.numpy())[0, len(prompt):])
    return outs, time.perf_counter() - t0


def pct(xs, q):
    return float(np.percentile(np.asarray(xs), q)) if xs else None


def main():
    paddle.seed(0)
    cfg = LlamaConfig.tiny(**MODEL_KW)
    model = LlamaForCausalLM(cfg)
    workload = make_workload(cfg)
    n_tokens = sum(p["max_new_tokens"] for _, _, p in WORKLOAD)

    # -- warmup: compile every executable both lanes will use ------------
    eng = serving.ServingEngine(model, max_slots=MAX_SLOTS, max_len=MAX_LEN,
                                max_queue_depth=len(workload))
    warm_reqs, _ = run_serving(eng, workload)
    refs, _ = run_sequential(model, workload)  # also the parity oracle

    parity = all(
        np.array_equal(np.asarray(r.result(timeout=1.0)), ref[:len(r.output_tokens)])
        and len(r.output_tokens) == len(ref)
        for r, ref in zip(warm_reqs, refs))

    # speculative engine: independent random draft (worst-case accept
    # rate) — the lane asserts the spec machinery NEVER changes output
    # and never recompiles, whatever the accept pattern
    paddle.seed(123)
    draft = LlamaForCausalLM(LlamaConfig.tiny(
        hidden_size=256, intermediate_size=512, num_hidden_layers=2,
        num_attention_heads=8, num_key_value_heads=4,
        vocab_size=MODEL_KW["vocab_size"]))
    spec_eng = serving.ServingEngine(
        model, draft_model=draft, max_slots=MAX_SLOTS, max_len=MAX_LEN,
        max_queue_depth=len(workload), spec_k=2)
    spec_warm, _ = run_serving(spec_eng, workload)
    spec_parity = all(
        r.result(timeout=1.0) == list(ref)
        for r, ref in zip(spec_warm, refs))

    # -- measured passes: 3 rounds per lane, ALTERNATING so an ambient
    # slowdown (shared box) hits every lane; keep each lane's best.
    # The tracing A/B rides in the same rotation: serving runs once with
    # tracing ON (the default) and once OFF per round — same engine,
    # same executables, the only delta is the host-side event recording.
    assert tracing.tracing_enabled(), "tracing must be default-on"
    step_before = recompile.entry_stats().get(
        "serving.step", {"compiles": 0, "retraces": 0})
    _SPEC_ENTRIES = ("serving.spec_draft", "serving.spec_verify")
    spec_before = {n: recompile.entry_stats().get(
        n, {"compiles": 0, "retraces": 0}) for n in _SPEC_ENTRIES}
    reqs, serving_wall = None, float("inf")
    seq_wall = float("inf")
    notrace_wall = float("inf")
    noperf_wall = float("inf")
    spec_wall = float("inf")
    for _ in range(3):
        r, w = run_serving(eng, workload)
        if w < serving_wall:
            reqs, serving_wall = r, w
        tracing.disable_tracing()
        try:
            _, w = run_serving(eng, workload)
        finally:
            tracing.enable_tracing()
        notrace_wall = min(notrace_wall, w)
        # perf capture A/B rides the same rotation: capture is
        # compile-time + an entry-exit clock read, so the ON lane (the
        # default everywhere else in this bench) should be at the noise
        # floor vs this OFF arm
        perf.disable()
        try:
            _, w = run_serving(eng, workload)
        finally:
            perf.enable()
        noperf_wall = min(noperf_wall, w)
        spec_r, w = run_serving(spec_eng, workload)
        spec_wall = min(spec_wall, w)
        spec_parity = spec_parity and all(
            r2.result(timeout=1.0) == list(ref)
            for r2, ref in zip(spec_r, refs))
        _, w = run_sequential(model, workload)
        seq_wall = min(seq_wall, w)
    step_after = recompile.entry_stats().get(
        "serving.step", {"compiles": 0, "retraces": 0})
    spec_after = {n: recompile.entry_stats().get(
        n, {"compiles": 0, "retraces": 0}) for n in _SPEC_ENTRIES}
    spec_compiles = sum(spec_after[n]["compiles"] - spec_before[n]["compiles"]
                        for n in _SPEC_ENTRIES)
    spec_retraces = sum(spec_after[n]["retraces"] - spec_before[n]["retraces"]
                        for n in _SPEC_ENTRIES)

    ttfts = [r.ttft_s for r in reqs if r.ttft_s is not None]
    tpots = [r.tpot_s for r in reqs if r.tpot_s is not None]
    serving_tps = n_tokens / serving_wall
    seq_tps = n_tokens / seq_wall
    notrace_tps = n_tokens / notrace_wall
    noperf_tps = n_tokens / noperf_wall
    # tracing is default-on: its cost is the A/B acceptance number
    # (<2% tok/s; negative = within noise, tracing side won the draw)
    tracing_overhead_pct = (notrace_tps - serving_tps) / notrace_tps * 100.0
    # perf capture is default-on too; same acceptance bound (<2%)
    perf_overhead_pct = (noperf_tps - serving_tps) / noperf_tps * 100.0
    result = {
        "bench": "serving_vs_sequential",
        "platform": jax.default_backend(),
        "model": {"family": "llama", **MODEL_KW},
        "requests": len(workload),
        "generated_tokens": n_tokens,
        "max_slots": MAX_SLOTS,
        "max_len": MAX_LEN,
        "serving": {
            "tok_s": round(serving_tps, 1),
            "wall_s": round(serving_wall, 3),
            "ttft_mean_s": round(float(np.mean(ttfts)), 4),
            "ttft_p95_s": round(pct(ttfts, 95), 4),
            "tpot_mean_s": round(float(np.mean(tpots)), 5),
            "tpot_p95_s": round(pct(tpots, 95), 5),
            "mean_occupancy": round(eng.mean_occupancy, 3),
        },
        "sequential": {
            "tok_s": round(seq_tps, 1),
            "wall_s": round(seq_wall, 3),
        },
        "speedup": round(serving_tps / seq_tps, 2),
        "per_request_parity": bool(parity),
        "step_compiles_measured_pass":
            step_after["compiles"] - step_before["compiles"],
        "step_retraces_measured_pass":
            step_after["retraces"] - step_before["retraces"],
        "tracing": {
            "on_tok_s": round(serving_tps, 1),
            "off_tok_s": round(notrace_tps, 1),
            "overhead_pct": round(tracing_overhead_pct, 2),
            "overhead_lt_2pct": bool(tracing_overhead_pct < 2.0),
            "zero_retraces_with_tracing":
                step_after["retraces"] == step_before["retraces"],
            "events_recorded": tracing.summary()["events_recorded"],
        },
        "perf_capture": {
            "on_tok_s": round(serving_tps, 1),
            "off_tok_s": round(noperf_tps, 1),
            "overhead_pct": round(perf_overhead_pct, 2),
            "overhead_lt_2pct": bool(perf_overhead_pct < 2.0),
            "ledger_entries": sorted(perf.ledger(prefix="serving.")),
            "step_roofline": (perf.ledger(prefix="serving.")
                              .get("serving.step", {}).get("roofline")),
        },
        "spec": {
            "spec_k": 2,
            "draft": "independent random 2-layer (adversarial accept "
                     "floor; see bench_spec_decode.py for the coupled "
                     "speedup lane)",
            "on_tok_s": round(n_tokens / spec_wall, 1),
            "off_tok_s": round(serving_tps, 1),
            "accept_rate": spec_eng.stats()["spec"]["accept_rate"],
            "per_request_parity": bool(spec_parity),
            "spec_compiles_measured_pass": spec_compiles,
            "spec_retraces_measured_pass": spec_retraces,
        },
    }

    path = os.path.join(HERE, "bench_serving.json")
    with open(path, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps(result, indent=1))
    print(f"[bench_serving] artifact -> {path}")

    ok = (parity and result["speedup"] > 1.0
          and result["step_compiles_measured_pass"] == 0
          and result["step_retraces_measured_pass"] == 0
          and result["tracing"]["overhead_lt_2pct"]
          and result["perf_capture"]["overhead_lt_2pct"]
          and spec_parity and spec_compiles == 0 and spec_retraces == 0)
    if not ok:
        print("[bench_serving] ACCEPTANCE FAILED", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
