"""One-off probe: NCHW vs NHWC conv stack timing on the real chip.

Representative ResNet-50 shapes (batch 256, bf16, fwd+bwd through a
bottleneck-like stack + BN + ReLU). Decides the layout for the vision
path (reference analogue: paddle/fluid/imperative/layout_autotune.cc
picks layouts dynamically; we measure once and bake the result in).
"""
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np


def _sync(r):
    # axon tunnel: block_until_ready does NOT round-trip; a scalar fetch does
    leaf = jax.tree_util.tree_leaves(r)[0]
    return float(jnp.ravel(leaf)[0].astype(jnp.float32))


def timeit(f, *args, n=20, warmup=3):
    for _ in range(warmup):
        r = f(*args)
    _sync(r)
    t0 = time.perf_counter()
    for _ in range(n):
        r = f(*args)
    _sync(r)
    return (time.perf_counter() - t0) / n


def make_stack(layout, wlayout):
    # stage-2-like: 28x28 feature maps, C=128/512 bottleneck x3
    dn = (layout, wlayout, layout)

    def block(x, ws):
        w1, w2, w3 = ws
        for w, st in ((w1, 1), (w2, 1), (w3, 1)):
            x = jax.lax.conv_general_dilated(
                x, w, (st, st), "SAME", dimension_numbers=dn)
            # BN-ish: normalize over all but channel axis, relu
            ch = 1 if layout == "NCHW" else 3
            axes = tuple(i for i in range(4) if i != ch)
            xf = x.astype(jnp.float32)
            m = jnp.mean(xf, axis=axes, keepdims=True)
            v = jnp.var(xf, axis=axes, keepdims=True)
            x = jnp.maximum((xf - m) * jax.lax.rsqrt(v + 1e-5),
                            0.0).astype(jnp.bfloat16)
        return x

    def loss(x, ws):
        return jnp.sum(block(x, ws).astype(jnp.float32))

    return jax.jit(jax.grad(loss, argnums=1)), block


def run(layout, wlayout):
    rng = np.random.RandomState(0)
    B, C, H = 256, 128, 28
    if layout == "NCHW":
        x = jnp.asarray(rng.randn(B, C, H, H), jnp.bfloat16)
    else:
        x = jnp.asarray(rng.randn(B, H, H, C), jnp.bfloat16)

    def w(kh, kw, ci, co):
        if wlayout == "OIHW":
            return jnp.asarray(rng.randn(co, ci, kh, kw) * 0.05, jnp.bfloat16)
        return jnp.asarray(rng.randn(kh, kw, ci, co) * 0.05, jnp.bfloat16)

    ws = (w(1, 1, C, C), w(3, 3, C, C), w(1, 1, C, C))
    g, _ = make_stack(layout, wlayout)
    dt = timeit(g, x, ws)
    flops = 2 * B * H * H * (C * C + 9 * C * C + C * C) * 3  # fwd
    print(f"{layout}/{wlayout}: {dt*1e3:.2f} ms  (~{3*flops/dt/1e12:.1f} TF/s fwd+bwd)")
    return dt


if __name__ == "__main__":
    print("devices:", jax.devices())
    run("NCHW", "OIHW")
    run("NHWC", "OIHW")
    run("NHWC", "HWIO")
