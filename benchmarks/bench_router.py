"""Multi-replica router lane: overhead vs direct engine, and
goodput/p99-TTFT with and without an injected replica crash.

Three lanes, one deterministic staggered workload (time-scheduled
arrivals, mixed greedy/sampled params — the continuous-batching case):

- ``overhead``: the same workload through ONE engine directly vs
  through a ``Router`` with that one engine as its only replica —
  best-of-3 alternating passes. The router is host-side bookkeeping
  (pick + relay + event wait), so the acceptance bar is <2% goodput
  loss at equal load; the measured number is pinned in
  ``perf_baseline.json`` (``router.overhead_pct``, direction lower).
- ``fleet_overhead``: the fleet observability plane's tax — one-replica
  router with the plane ON (trace propagation, metric federation, SLO
  tracking, straggler scan — the default) vs ``fleet_observability=
  False``, best-of-3 alternating; the ON passes also exercise the
  federated exposition and SLO report. Bars: <2% goodput delta
  (``router.fleet_overhead_pct`` pinned in ``perf_baseline.json``) and
  ZERO retraces — the plane is host-side bookkeeping, it must never
  touch the compiled surface.
- ``goodput``: 2 replicas, no faults — fleet tok/s, goodput (deadline-
  met tok/s), and the TTFT p50/p95/p99 tail.
- ``crash``: the same 2-replica fleet with replica r0 killed
  mid-decode (``ChaosEngine``, step-count-deterministic). EVERY request
  must still complete — failover retries on r1 — with outputs
  bit-identical to ``generation.generate`` (asserted for all requests,
  greedy AND sampled), zero retraces on the surviving replica, and
  amplification under the cap. The p99 TTFT with the crash quantifies
  the failover tax.

Artifact: ``benchmarks/bench_router.json``; ``tests/run_shards.py``
folds it into ``telemetry_lane.json`` as ``router_bench`` and the perf
gate reads ``router.tok_s`` / ``router.overhead_pct`` /
``router.crash_completed_frac`` from it. Exit code is non-zero when a
verdict fails. CPU numbers size the lane on the dev box; the chip lane
reruns for real ones.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import generation, serving
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import recompile

HERE = os.path.dirname(os.path.abspath(__file__))

# (arrival_offset_s, prompt_len, params): arrivals stagger over ~0.5 s
# so later requests land while earlier ones decode.
WORKLOAD = [
    (0.00, 5, dict(max_new_tokens=40)),
    (0.00, 9, dict(max_new_tokens=32, do_sample=True, temperature=0.8,
                   top_k=8, seed=1)),
    (0.03, 14, dict(max_new_tokens=48)),
    (0.06, 26, dict(max_new_tokens=24, do_sample=True, top_p=0.9, seed=2)),
    (0.09, 7, dict(max_new_tokens=40)),
    (0.12, 11, dict(max_new_tokens=24, do_sample=True, temperature=1.1,
                    top_k=12, seed=3)),
    (0.16, 19, dict(max_new_tokens=32)),
    (0.20, 4, dict(max_new_tokens=16)),
    (0.25, 30, dict(max_new_tokens=40, do_sample=True, top_k=64,
                    top_p=0.95, seed=4)),
    (0.30, 6, dict(max_new_tokens=32)),
    (0.36, 13, dict(max_new_tokens=24, do_sample=True, temperature=0.9,
                    top_k=6, seed=5)),
    (0.42, 8, dict(max_new_tokens=40)),
    (0.46, 10, dict(max_new_tokens=28)),
    (0.50, 16, dict(max_new_tokens=32, do_sample=True, top_k=16, seed=6)),
]
MAX_SLOTS = 4
MAX_LEN = 96
DEADLINE_S = 60.0

# weight-streaming-bound decode (the serving regime) but small enough
# that six engine builds fit the lane budget
MODEL_KW = dict(hidden_size=256, intermediate_size=512,
                num_hidden_layers=3, num_attention_heads=8,
                num_key_value_heads=4, vocab_size=2048)


def make_workload(cfg):
    rng = np.random.RandomState(42)
    return [(at, rng.randint(1, cfg.vocab_size, n).astype(np.int32), p)
            for at, n, p in WORKLOAD]


def reference_outputs(model, workload):
    return [generation.generate(model, prompt[None], **params)
            .numpy()[0, len(prompt):]
            for _, prompt, params in workload]


def new_engine(model):
    eng = serving.ServingEngine(model, max_slots=MAX_SLOTS, max_len=MAX_LEN)
    eng.warmup()
    return eng


def run_workload(submit, workload):
    """Time-scheduled submission; returns (handles, tok_s, wall_s,
    ttft_list)."""
    handles = []
    t0 = time.perf_counter()
    for at, prompt, params in workload:
        while time.perf_counter() - t0 < at:
            time.sleep(0.002)
        handles.append(submit(prompt, params))
    for h in handles:
        try:
            h.result(timeout=DEADLINE_S + 30)
        except TimeoutError:
            pass
    wall = time.perf_counter() - t0
    tokens = sum(len(h.output_tokens) for h in handles)
    ttfts = [h.ttft_s for h in handles if h.ttft_s is not None]
    return handles, tokens / wall, wall, ttfts


def pct(values, q):
    if not values:
        return None
    return float(np.percentile(np.asarray(values), q))


def ttft_block(ttfts):
    return {"p50_ms": round(1e3 * pct(ttfts, 50), 2),
            "p95_ms": round(1e3 * pct(ttfts, 95), 2),
            "p99_ms": round(1e3 * pct(ttfts, 99), 2)}


def serving_retraces():
    return sum(v["retraces"] for k, v in recompile.entry_stats().items()
               if k.startswith("serving."))


def lane_overhead(model, workload):
    """Direct engine vs router-with-one-replica, best-of-3 alternating
    passes over the SAME engines (steady-state: both warmed)."""
    direct_eng = new_engine(model).start()
    router_eng = new_engine(model)
    router = serving.Router([router_eng], probe_interval_s=0.5)
    router.start()

    def submit_direct(prompt, params):
        return direct_eng.submit(prompt, deadline_s=DEADLINE_S,
                                 params=serving.SamplingParams(**params))

    def submit_router(prompt, params):
        return router.submit(prompt, deadline_s=DEADLINE_S,
                             params=serving.SamplingParams(**params))

    best = {"direct": 0.0, "router": 0.0}
    for _ in range(3):
        for name, submit in (("direct", submit_direct),
                             ("router", submit_router)):
            _, tok_s, _, _ = run_workload(submit, workload)
            best[name] = max(best[name], tok_s)
    overhead_pct = 100.0 * (1.0 - best["router"] / best["direct"])
    router.stop(drain=True, timeout_s=30)
    direct_eng.stop()
    return {"direct_tok_s": round(best["direct"], 1),
            "router_tok_s": round(best["router"], 1),
            "overhead_pct": round(overhead_pct, 2),
            "passes": 3,
            "verdict_lt_2pct": overhead_pct < 2.0}


def lane_fleet_overhead(model, workload):
    """Fleet-observability-plane tax: the same workload through two
    single-replica routers, one with the plane ON (trace propagation +
    metric federation + SLO tracking + straggler scan — the default)
    and one with ``fleet_observability=False``. Best-of-3 alternating
    passes; the ON pass also hits the federated exposition and the SLO
    report mid-run so the scrape/render path is in the measured window,
    not idle. Acceptance: <2% goodput delta and zero retraces (the
    plane is host-side bookkeeping — it must never touch the compiled
    surface)."""
    eng_on = new_engine(model)
    eng_off = new_engine(model)
    router_on = serving.Router([eng_on], probe_interval_s=0.5)
    router_off = serving.Router(
        [eng_off], serving.RouterConfig(probe_interval_s=0.5,
                                        fleet_observability=False))
    router_on.start()
    router_off.start()

    def make_submit(router):
        def submit(prompt, params):
            return router.submit(prompt, deadline_s=DEADLINE_S,
                                 params=serving.SamplingParams(**params))
        return submit

    retr0 = serving_retraces()
    best = {"on": 0.0, "off": 0.0}
    for _ in range(3):
        for name, router in (("off", router_off), ("on", router_on)):
            _, tok_s, _, _ = run_workload(make_submit(router), workload)
            if name == "on":
                # the consumer side of the plane, inside the window
                router.federated_metrics_text()
                router.slo_report()
            best[name] = max(best[name], tok_s)
    new_retraces = serving_retraces() - retr0
    overhead_pct = 100.0 * (1.0 - best["on"] / best["off"])
    fed = router_on.stats()["fleet"]["federation"]
    router_on.stop(drain=True, timeout_s=30)
    router_off.stop(drain=True, timeout_s=30)
    return {"on_tok_s": round(best["on"], 1),
            "off_tok_s": round(best["off"], 1),
            "overhead_pct": round(overhead_pct, 2),
            "passes": 3,
            "fleet_scrapes": fed.get("scrapes", 0),
            "new_retraces": new_retraces,
            "verdict_lt_2pct": overhead_pct < 2.0}


def lane_goodput(model, workload, refs, crash: bool):
    engines = [new_engine(model), new_engine(model)]
    router = serving.Router(
        engines, probe_interval_s=0.05, probe_failures_to_eject=2,
        max_retries_per_request=2, unroutable_timeout_s=30.0)
    router.start()
    monkey = None
    if crash:
        # deterministic mid-run kill: r0 dies ~30 loop iterations in
        monkey = serving.ChaosEngine(engines[0]).crash_after_steps(30)
    retr0 = serving_retraces()

    def submit(prompt, params):
        return router.submit(prompt, deadline_s=DEADLINE_S,
                             params=serving.SamplingParams(**params))

    handles, tok_s, wall, ttfts = run_workload(submit, workload)
    completed = [h for h in handles
                 if h.status == serving.RequestStatus.COMPLETED]
    lost = [h for h in handles if not h.done]
    parity = all(
        np.array_equal(np.asarray(h.output_tokens), ref)
        for h, ref in zip(handles, refs)
        if h.status == serving.RequestStatus.COMPLETED)
    deadline_met_tokens = sum(
        len(h.output_tokens) for h in completed
        if h.finish_ts - h.arrival_ts <= DEADLINE_S)
    st = router.stats()
    out = {
        "replicas": 2,
        "requests": len(handles),
        "completed": len(completed),
        "completed_frac": round(len(completed) / len(handles), 4),
        "silently_lost": len(lost),
        "tok_s": round(tok_s, 1),
        "goodput_tok_s": round(deadline_met_tokens / wall, 1),
        "wall_s": round(wall, 3),
        "ttft": ttft_block(ttfts),
        "retries": sum(h.retries for h in handles),
        "extra_attempts": st["extra_attempts"],
        "amplification": st["amplification"],
        "parity_vs_generate": parity,
        "new_retraces": serving_retraces() - retr0,
    }
    if crash:
        out["crash_injected"] = monkey.injected["crash"]
        out["replica_states"] = {r["name"]: r["state"]
                                 for r in router.replicas()}
    router.stop(drain=True, timeout_s=30)
    return out


def main():
    paddle.seed(0)
    cfg = LlamaConfig(**MODEL_KW)
    model = LlamaForCausalLM(cfg)
    workload = make_workload(cfg)
    print(f"[bench_router] model {MODEL_KW['hidden_size']}h x "
          f"{MODEL_KW['num_hidden_layers']}L, {len(workload)} requests",
          flush=True)
    refs = reference_outputs(model, workload)

    overhead = lane_overhead(model, workload)
    print(f"[bench_router] overhead: direct {overhead['direct_tok_s']} "
          f"tok/s vs router {overhead['router_tok_s']} tok/s -> "
          f"{overhead['overhead_pct']}% (<2% verdict: "
          f"{overhead['verdict_lt_2pct']})", flush=True)

    fleet = lane_fleet_overhead(model, workload)
    print(f"[bench_router] fleet plane: off {fleet['off_tok_s']} tok/s "
          f"vs on {fleet['on_tok_s']} tok/s -> {fleet['overhead_pct']}% "
          f"(<2% verdict: {fleet['verdict_lt_2pct']}, new retraces "
          f"{fleet['new_retraces']})", flush=True)

    goodput = lane_goodput(model, workload, refs, crash=False)
    print(f"[bench_router] 2-replica goodput {goodput['goodput_tok_s']} "
          f"tok/s, TTFT p99 {goodput['ttft']['p99_ms']} ms", flush=True)

    crash = lane_goodput(model, workload, refs, crash=True)
    print(f"[bench_router] crash lane: {crash['completed']}/"
          f"{crash['requests']} completed (retries {crash['retries']}), "
          f"TTFT p99 {crash['ttft']['p99_ms']} ms, parity "
          f"{crash['parity_vs_generate']}, new retraces "
          f"{crash['new_retraces']}", flush=True)

    verdicts = {
        "overhead_lt_2pct": overhead["verdict_lt_2pct"],
        "fleet_overhead_lt_2pct": fleet["verdict_lt_2pct"],
        "fleet_zero_retraces": fleet["new_retraces"] == 0,
        "no_silent_loss": goodput["silently_lost"] == 0
        and crash["silently_lost"] == 0,
        "crash_all_completed": crash["completed_frac"] == 1.0,
        "crash_parity": crash["parity_vs_generate"],
        "crash_fault_fired": crash.get("crash_injected", 0) >= 1,
        "zero_retraces_on_survivors": crash["new_retraces"] == 0
        and goodput["new_retraces"] == 0,
        "amplification_bounded": crash["extra_attempts"]
        <= 0.5 * crash["requests"] + 4,
    }
    out = {
        "model": MODEL_KW,
        "workload_requests": len(workload),
        "max_slots": MAX_SLOTS,
        "overhead": overhead,
        "fleet_overhead": fleet,
        "goodput": goodput,
        "crash": crash,
        "verdicts": verdicts,
    }
    path = os.path.join(HERE, "bench_router.json")
    with open(path, "w") as fh:
        json.dump(out, fh, indent=1)
    print(f"[bench_router] -> {path}", flush=True)
    failed = [k for k, v in verdicts.items() if not v]
    if failed:
        print(f"[bench_router] VERDICTS FAILED: {failed}", flush=True)
        return 1
    print("[bench_router] all verdicts passed", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
