"""Ring-attention microbench: per-hop kernel timing on the real chip +
multi-device correctness/shape of the full ring on the CPU mesh.

The full ring (distributed/sequence_parallel.py ring_attention) runs
under shard_map, which cannot execute on the single-chip axon tunnel
(documented in .claude/skills/verify). What CAN be measured on the chip
is the ring's inner per-hop update — blockwise attention of the local Q
shard against the resident KV block with online-softmax accumulation —
which is the compute a real n-chip ring runs n times per layer while
ppermute rotates KV over ICI (the transfer overlaps compute: a KV block
is 2*s_loc*h*d*2 bytes vs ~45 GB/s per ICI link on v5e, a small fraction
of the hop's compute time at these shapes).

Writes benchmarks/ring_attention_results.json:
  hop_ms        — measured per-hop time (chained-scan method, see
                  bench_flash_attention.py for why)
  ring_step_ms  — n_ranks * hop_ms (per layer, per ring pass)
  est_tflops    — achieved TF/s on the hop's useful flops

Run: python benchmarks/bench_ring_attention.py  (on the chip)
"""

from __future__ import annotations

import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from benchmarks.bench_flash_attention import bench


def ring_hop(qm, km, vm, o, lse):
    """One ring hop (mirrors sequence_parallel.ring_attention's block
    body, minus the ppermute): the Pallas flash kernel consumes the
    resident KV block (no [sl, sl] score tensor in HBM) and the
    normalized partial merges through its log-sum-exp. Shapes [bh, sl,
    d]; non-causal hop (the common case — n-1 of n hops)."""
    from paddle_tpu.pallas_kernels.flash_attention import _flash_lse

    sl, d = qm.shape[1], qm.shape[2]
    o_i, lse_i = _flash_lse(qm, km, vm, None, False, 1.0 / math.sqrt(d),
                            min(1024, sl), min(1024, sl))
    lse_new = jnp.logaddexp(lse, lse_i)
    o_new = (o * jnp.exp(lse - lse_new)[..., None]
             + o_i.astype(jnp.float32) * jnp.exp(lse_i - lse_new)[..., None])
    return o_new, lse_new


def main():
    n_ranks = int(os.environ.get("RING_RANKS", "8"))
    b, h, d = 1, 12, 64
    s_global = int(os.environ.get("RING_SEQ", "32768"))
    s_loc = s_global // n_ranks

    rng = np.random.RandomState(0)
    qm = jnp.asarray(rng.randn(b * h, s_loc, d), jnp.bfloat16)
    km = jnp.asarray(rng.randn(b * h, s_loc, d), jnp.bfloat16)
    vm = jnp.asarray(rng.randn(b * h, s_loc, d), jnp.bfloat16)
    o = jnp.zeros((b * h, s_loc, d), jnp.float32)
    lse = jnp.full((b * h, s_loc), -jnp.inf, jnp.float32)

    def hop(qm, km, vm, o, lse):
        o2, lse2 = ring_hop(qm, km, vm, o, lse)
        # fold o2 into the qm chain: the bench returns carry[0], and
        # without this dependence XLA dead-code-eliminates the whole hop
        return (qm + o2.astype(qm.dtype) * 1e-6, km, vm, o2, lse2)

    hop_s = bench(lambda *a: hop(*a), qm, km, vm, o, lse, iters=50)
    flops = 2 * 2 * b * h * s_loc * s_loc * d  # QK^T + PV
    out = {
        "backend": jax.default_backend(),
        "n_ranks": n_ranks,
        "seq_global": s_global,
        "seq_local": s_loc,
        "hop_ms": round(hop_s * 1e3, 3),
        "ring_step_ms": round(hop_s * 1e3 * n_ranks, 3),
        "est_tflops": round(flops / hop_s / 1e12, 1),
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "ring_attention_results.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
