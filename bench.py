"""Benchmark entry point: Llama pretrain step throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Metric: tokens/sec/chip on a Llama decoder pretrain step (the BASELINE.json
north-star metric family), measured with warmup-skip semantics matching the
reference's profiler ips counter (python/paddle/profiler/timer.py).

Two model points:
- 134M (hidden 768 x 12L, seq 1024, flash attention): the primary metric;
  r01 recorded 106,650 tok/s/chip as the regression floor.
- ~0.9B (hidden 1536 x 24L) with remat + ZeRO-style optimizer-state
  layout: the memory-stressed point; reported in detail with achieved MFU
  (peak = 197 TFLOP/s bf16 on v5e).
"""

from __future__ import annotations

import json
import os
import time

import jax
import numpy as np


def _v5e_peak_flops():
    # the observability peak table (env override PADDLE_TPU_PEAK_FLOPS,
    # per-chip specs keyed by jax's device_kind) wins when it knows the
    # attached device; the auto-tuner's v5e default stays the fallback
    # so MFU numbers on unknown kinds keep their historical meaning
    try:
        from paddle_tpu.observability.perf import peak_specs

        peak = peak_specs()["peak_flops_per_s"]
        if peak:
            return peak
    except Exception:
        pass
    from paddle_tpu.distributed.auto_tuner import _HW_DEFAULTS

    return _HW_DEFAULTS["peak_tflops"] * 1e12


def _bf16_llama(model):
    """Cast to bf16 but keep the RoPE tables fp32 (position phases lose
    too much precision in bf16; the matmuls stay bf16 either way)."""
    model.to(dtype="bfloat16")
    model.llama.rope_cos._data = model.llama.rope_cos._data.astype(np.float32)
    model.llama.rope_sin._data = model.llama.rope_sin._data.astype(np.float32)


def _timed(step_fn, steps, warmup, *, entry="bench", items_per_step=None):
    """Warmup-skip timing window (reference profiler/timer.py ips
    semantics): run ``warmup`` steps, sync, time ``steps`` steps, sync.
    Returns (elapsed_seconds, last_loss, step_records).

    The timed window is driven through the profiler ips timer with an
    observability.StepTelemetry attached, so every bench point emits the
    per-step telemetry stream (step time, items/s, memory watermarks,
    compile-count deltas) the BENCH artifact is derived from —
    ``PADDLE_TPU_TELEMETRY_JSONL=path`` additionally lands one JSONL
    line per step. The elapsed seconds are integrated from that stream;
    the float() on the loss is the synchronization point that bounds the
    measured window (executed INSIDE the last step so the stream total
    covers the same window)."""
    import os

    from paddle_tpu import observability, profiler

    loss = None
    for _ in range(warmup):
        loss = step_fn()
    if loss is not None:
        _ = float(loss)
    st = observability.StepTelemetry(
        entry=entry, jsonl_path=os.environ.get("PADDLE_TPU_TELEMETRY_JSONL"))
    bm = profiler.benchmark()
    wall0 = time.time()
    bm.begin()
    st.attach_benchmark()
    try:
        for i in range(steps):
            loss = step_fn()
            if i == steps - 1:
                _ = float(loss)  # sync: the last record absorbs the drain
            bm.step(items_per_step)
    finally:
        bm.end()
        st.close()
    recs = [r for r in st.records() if r["ts"] >= wall0]
    dt = sum(r["step_time_s"] for r in recs) or 1e-9
    return dt, loss, recs


def _run_config(paddle, cfg, batch, seq, steps, warmup, *, remat=False,
                shard_opt=False, report_hbm=False):
    from paddle_tpu.distributed.engine import ShardedTrainStep
    from paddle_tpu.distributed.mesh import ProcessMesh
    from paddle_tpu.models import LlamaForCausalLM, llama_pretrain_loss

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    on_tpu = jax.default_backend() not in ("cpu",)
    if on_tpu:
        _bf16_llama(model)

    n_dev = len(jax.devices())
    mesh = ProcessMesh(np.arange(n_dev), ["dp"])
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())
    step = ShardedTrainStep(model, llama_pretrain_loss, opt, mesh,
                            dp_axis="dp" if n_dev > 1 else None,
                            remat=remat, shard_optimizer_states=shard_opt)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))

    dt, loss, _recs = _timed(
        lambda: step.step(ids, labels), steps, warmup,
        entry=f"llama_h{cfg.hidden_size}_s{seq}", items_per_step=batch * seq)

    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    tokens_per_sec = batch * seq * steps / dt
    # PaLM-convention training FLOPs/token: 6N plus attention 12*L*s*h;
    # MFU only meaningful against the TPU peak (null on the CPU smoke path)
    flops_per_token = 6 * n_params + 12 * cfg.num_hidden_layers * seq * cfg.hidden_size
    mfu = (tokens_per_sec * flops_per_token / (_v5e_peak_flops() * max(n_dev, 1))
           if on_tpu else None)
    out = {
        "tokens_per_sec_per_chip": round(tokens_per_sec / max(n_dev, 1), 2),
        "params_m": round(n_params / 1e6, 1),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "final_loss": round(float(loss), 4),
        "batch": batch, "seq": seq,
        "hidden": cfg.hidden_size, "layers": cfg.num_hidden_layers,
    }
    if remat:
        out["remat"] = remat if isinstance(remat, str) else "full"
    if report_hbm:
        # per-program HBM breakdown from XLA (args ≈ params+opt state,
        # temps ≈ activations); device memory_stats is process-cumulative
        # (and absent on some PJRT transports), so the compiled-program
        # analysis is the per-config number
        try:
            ma = step.memory_analysis(ids, labels)
            if ma and ma.get("temp_bytes") is not None:
                out["hbm_args_gb"] = round((ma["argument_bytes"] or 0) / 2**30, 2)
                out["hbm_temps_gb"] = round(ma["temp_bytes"] / 2**30, 2)
        except Exception:
            pass
    return out


def _run_offload_config(paddle):
    """~2B-param single-chip point: only fits because optimizer state is
    host-offloaded (device = bf16 params + bf16 grad accumulator)."""
    import jax.numpy as jnp

    from paddle_tpu.distributed.mesh import ProcessMesh
    from paddle_tpu.distributed.offload import (HostOffloadAdamW,
                                                HostOffloadTrainStep)
    from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                   llama_pretrain_loss)

    paddle.seed(0)
    cfg = LlamaConfig(
        vocab_size=32000, hidden_size=2560, intermediate_size=6912,
        num_hidden_layers=24, num_attention_heads=20, num_key_value_heads=20,
        max_position_embeddings=2048, use_flash_attention=True,
        dtype="bfloat16")
    model = LlamaForCausalLM(cfg)
    _bf16_llama(model)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    ACC, B, S = 24, 4, 1024
    step = HostOffloadTrainStep(
        model, llama_pretrain_loss, ProcessMesh(np.arange(1), ["dp"]),
        accum_steps=ACC, learning_rate=1e-4, accum_dtype=jnp.bfloat16)
    kinds = HostOffloadAdamW.state_memory_kinds(step.opt_state)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32))
    # warmup = one full accumulation cycle: compiles accum + per-shape updates
    dt, loss, _recs = _timed(lambda: step.step(ids, labels), ACC, ACC,
                             entry="llama2b_offload", items_per_step=B * S)
    tps = B * S * ACC / dt
    fpt = 6 * n_params + 12 * cfg.num_hidden_layers * S * cfg.hidden_size
    return {
        "tokens_per_sec_per_chip": round(tps, 2),
        "params_m": round(n_params / 1e6, 1),
        "mfu": round(tps * fpt / _v5e_peak_flops(), 4),
        "final_loss": round(float(loss), 4),
        "batch": B, "seq": S, "accum_steps": ACC,
        "hidden": cfg.hidden_size, "layers": cfg.num_hidden_layers,
        "opt_state_memory": sorted(kinds),
        "opt_state_gb_host": round(3 * 4 * n_params / 2**30, 1),
        "accum_dtype": "bfloat16",
    }


def _run_resnet50(paddle):
    """ResNet-50 train step images/sec — BASELINE.json's second headline
    metric family (PaddleClas ResNet-50, reference config 2). bf16 params
    + batch, Momentum(+wd) update, whole step one XLA program; MFU from
    the compiled program's own cost analysis (conv FLOPs, not the LLM 6N
    estimate)."""
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed.engine import ShardedTrainStep
    from paddle_tpu.distributed.mesh import ProcessMesh
    from paddle_tpu.vision.models import resnet50

    from paddle_tpu.nn.layout import space_to_depth_stem

    paddle.seed(0)
    model = resnet50(num_classes=1000)
    model.to(dtype="bfloat16")
    paddle.nn.to_channels_last(model)  # NHWC internals: TPU conv layout
    space_to_depth_stem(model)  # 7x7/s2 stem -> packed 4x4 (MXU lanes)
    opt = paddle.optimizer.Momentum(
        learning_rate=0.1, momentum=0.9, weight_decay=1e-4,
        parameters=model.parameters())

    def loss_fn(logits, labels):
        return F.cross_entropy(logits, labels).mean()

    mesh = ProcessMesh(np.arange(1), ["dp"])
    step = ShardedTrainStep(model, loss_fn, opt, mesh, dp_axis=None)

    B = 256
    rng = np.random.RandomState(0)
    import jax.numpy as jnp
    x = paddle.to_tensor(jnp.asarray(rng.randn(B, 3, 224, 224), jnp.bfloat16))
    y = paddle.to_tensor(rng.randint(0, 1000, (B,)).astype(np.int64))

    # 30 timed steps: the tunnel's ~90ms result-fetch round trip is paid
    # once per window, so a short window understates device throughput
    steps, warmup = 30, 3
    dt, loss, _recs = _timed(lambda: step.step(x, y), steps, warmup,
                             entry="resnet50", items_per_step=B)
    images_per_sec = B * steps / dt
    from paddle_tpu.nn.layers_conv_norm import fused_conv_enabled

    out = {
        "images_per_sec": round(images_per_sec, 1),
        "batch": B,
        "final_loss": round(float(loss), 4),
        # Pallas conv+BN+ReLU fusion (pallas_kernels/fused_conv.py):
        # default-on for TPU backends, PADDLE_TPU_FUSED_CONV=0 disables
        "fused_conv": fused_conv_enabled(),
    }
    try:
        ca = step.cost_analysis(x, y)
        if ca and ca.get("flops"):
            out["step_tflops"] = round(ca["flops"] / 1e12, 2)
            out["mfu"] = round(
                (images_per_sec / B) * ca["flops"] / _v5e_peak_flops(), 4)
    except Exception:
        pass
    return out


def _run_moe(paddle):
    """MoE point: the 134M-class decoder with every MLP an 8-expert
    GShard MoE (topk 2) — measures the routing + batched-expert-einsum
    path (reference: incubate fused MoE kernels). MFU against ACTIVE
    params (6N convention counts only the topk experts a token visits)."""
    from paddle_tpu.distributed.engine import ShardedTrainStep
    from paddle_tpu.distributed.mesh import ProcessMesh
    from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                   moe_pretrain_loss)

    paddle.seed(0)
    # capacity_factor 1.0: exactly t*topk expert slots — the 1.25 default
    # pads 25% dead compute into the expert matmuls; with the aux loss
    # balancing load, the drop rate at 1.0 is small and the loss curve
    # tracks (A/B'd on chip: same loss to 4 decimals, +7% tok/s)
    cfg = LlamaConfig(
        vocab_size=32000, hidden_size=768, intermediate_size=2048,
        num_hidden_layers=12, num_attention_heads=12, num_key_value_heads=12,
        max_position_embeddings=2048, use_flash_attention=True,
        moe_num_experts=8, moe_topk=2, moe_capacity_factor=1.0,
        dtype="bfloat16")
    model = LlamaForCausalLM(cfg)
    _bf16_llama(model)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())
    step = ShardedTrainStep(model, moe_pretrain_loss(model), opt,
                            ProcessMesh(np.arange(1), ["dp"]), dp_axis=None)
    B, S = 16, 1024
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32))
    # 60-step window: the tunnel's ~90 ms fetch is per-window; a short
    # window would understate device throughput by ~2%
    dt, loss, _recs = _timed(lambda: step.step(ids, labels), 60, 4,
                             entry="moe", items_per_step=B * S)
    tps = B * S * 60 / dt
    n_total = n_expert = 0
    for name, p in model.named_parameters_dict().items():
        n = int(np.prod(p.shape))
        n_total += n
        if ".experts." in name:
            n_expert += n
    n_active = n_total - n_expert + n_expert * cfg.moe_topk // cfg.moe_num_experts
    fpt = 6 * n_active + 12 * cfg.num_hidden_layers * S * cfg.hidden_size
    return {
        "tokens_per_sec_per_chip": round(tps, 2),
        "params_m_total": round(n_total / 1e6, 1),
        "params_m_active": round(n_active / 1e6, 1),
        "mfu_active": round(tps * fpt / _v5e_peak_flops(), 4),
        "final_loss": round(float(loss), 4),
        "batch": B, "seq": S, "experts": cfg.moe_num_experts,
        "topk": cfg.moe_topk,
    }


def _run_decode(paddle, cfg, *, weight_only_int8=False, batch=16):
    """Serving-side point: autoregressive decode throughput with the
    static-KV-cache jitted step (generation.py; reference surface =
    inference predictor + PaddleNLP generation loop). Whole second
    generate() call timed — compiled prefill + N-1 donated decode steps.
    ``weight_only_int8``: nn.quant weight-only serving path (half the
    weight bytes on the bandwidth-bound decode)."""
    from paddle_tpu.models import LlamaForCausalLM

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    _bf16_llama(model)
    if weight_only_int8:
        from paddle_tpu.nn.quant import quantize_for_inference

        quantize_for_inference(model)
    B, S, N = batch, 128, 256
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32))
    out = model.generate(ids, max_new_tokens=N)
    np.asarray(out.numpy())  # sync: compile + warmup execution fully drained
    # best-of-3: a single ~0.3s generate is noise-prone over the remote
    # PJRT transport (one RPC hiccup skews it ±15%)
    dts = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = model.generate(ids, max_new_tokens=N)
        np.asarray(out.numpy())  # sync
        dts.append(time.perf_counter() - t0)
    dt = min(dts)
    return {
        "decode_tokens_per_sec": round(B * N / dt, 1),
        "ms_per_token": round(1e3 * dt / N, 3),
        "batch": B, "prompt": S, "new_tokens": N,
    }


def _telemetry_summary():
    """Aggregates from the observability stream for the bench artifact:
    compile counts/seconds, retraces, fused-conv dispatch outcomes —
    the numbers BENCH_r*.json used to reconstruct by hand."""
    from paddle_tpu import observability as obs

    snap = obs.snapshot()
    fams = snap["metrics"]

    def series(name):
        fam = fams.get(name)
        return fam["samples"] if fam else []

    return {
        "compiles_total": int(sum(
            s["value"] for s in series("paddle_tpu_compiles_total"))),
        "compile_seconds_total": round(sum(
            s.get("sum", 0.0) for s in series("paddle_tpu_compile_seconds")), 2),
        "retraces_total": int(sum(
            s["value"] for s in series("paddle_tpu_retraces_total"))),
        "fused_conv_dispatch": {
            "/".join(s["labels"].values()): int(s["value"])
            for s in series("paddle_tpu_fused_conv_dispatch_total")},
        "steps_recorded": len(snap["steps"]),
        # the tracing half rides along: total events + the generation
        # phase spans recorded while the bench points ran
        "trace_events_recorded": snap["tracing"]["events_recorded"],
        "trace_spans": {
            k: v for k, v in snap["tracing"]["span_counts"].items()
            if k.startswith(("generation.", "serving."))},
    }


def main():
    # persistent compilation cache: ~15 min of the full bench is XLA
    # compiles; repeat runs (and the driver's bench phase after a local
    # run) hit the disk cache instead. /tmp: per-machine, never committed.
    try:
        import os
        import tempfile

        cache_dir = os.path.join(tempfile.gettempdir(),
                                 f"paddle_tpu_xla_cache_{os.getuid()}")
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # older jax without the knobs: compile as usual

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig

    backend = jax.default_backend()
    on_tpu = backend not in ("cpu",)

    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=768, intermediate_size=2048,
            num_hidden_layers=12, num_attention_heads=12, num_key_value_heads=12,
            max_position_embeddings=2048, use_flash_attention=True, dtype="bfloat16")
        # one retry: the remote PJRT transport occasionally drops an RPC
        # mid-run; a transient must not zero out the whole bench artifact
        try:
            primary = _run_config(paddle, cfg, batch=16, seq=1024, steps=30,
                                  warmup=3)
        except Exception:
            primary = _run_config(paddle, cfg, batch=16, seq=1024, steps=30,
                                  warmup=3)
    else:  # CI smoke path
        primary = _run_config(paddle, LlamaConfig.tiny(), batch=4, seq=64,
                              steps=5, warmup=2)

    detail = {"backend": backend, "n_devices": len(jax.devices()), **primary}

    if on_tpu:
        # memory-stressed point: ~0.9B params, SELECTIVE remat (save MXU
        # dot outputs, recompute elementwise — reference recompute modes,
        # fleet/recompute/recompute.py:124) + sharded opt states
        try:
            big = LlamaConfig(
                vocab_size=32000, hidden_size=1536, intermediate_size=4096,
                num_hidden_layers=24, num_attention_heads=16,
                num_key_value_heads=16, max_position_embeddings=2048,
                use_flash_attention=True, dtype="bfloat16")
            detail["big_model"] = _run_config(
                paddle, big, batch=8, seq=1024, steps=5, warmup=2,
                remat="dots_with_no_batch_dims_saveable", shard_opt=True,
                report_hbm=True)
        except Exception as e:  # noqa: BLE001 — degrade to the primary point
            detail["big_model_error"] = f"{type(e).__name__}: {e}"[:200]

        # host-offload point: ~2B params on ONE 16 GB chip — fp32 AdamW
        # master/m/v (24 GB) live in pinned host memory and stream through
        # the chip once per 24-micro-batch accumulation cycle
        # (distributed/offload.py; reference group_sharded stage-3
        # offload=True + gradient_merge)
        try:
            detail["big2b_offload"] = _run_offload_config(paddle)
        except Exception as e:  # noqa: BLE001
            detail["big2b_offload_error"] = f"{type(e).__name__}: {e}"[:200]

        # long-sequence point: seq 4096 where the Pallas flash-attention
        # kernel's advantage over XLA dense is largest (1.9-2.3x microbench)
        try:
            long_cfg = LlamaConfig(
                vocab_size=32000, hidden_size=768, intermediate_size=2048,
                num_hidden_layers=12, num_attention_heads=12,
                num_key_value_heads=12, max_position_embeddings=4096,
                use_flash_attention=True, dtype="bfloat16")
            detail["seq4096"] = _run_config(
                paddle, long_cfg, batch=4, seq=4096, steps=15, warmup=2)
        except Exception as e:  # noqa: BLE001
            detail["seq4096_error"] = f"{type(e).__name__}: {e}"[:200]

    if on_tpu:
        # long-context point: seq 8192 on one chip — exercises the raised
        # Mosaic scoped-VMEM cap (pallas_kernels/flash_attention.py
        # _vmem_params) that the backward kernels need at this length
        try:
            cfg8k = LlamaConfig(
                vocab_size=32000, hidden_size=768, intermediate_size=2048,
                num_hidden_layers=12, num_attention_heads=12,
                num_key_value_heads=12, max_position_embeddings=8192,
                use_flash_attention=True, dtype="bfloat16")
            detail["seq8192"] = _run_config(
                paddle, cfg8k, batch=2, seq=8192, steps=15, warmup=2)
        except Exception as e:  # noqa: BLE001
            detail["seq8192_error"] = f"{type(e).__name__}: {e}"[:200]

        # seq 16384 measured (round-5: was a capability assert only):
        # single-chip ceiling documented in flash_attention.py — no remat
        # (A/B'd: dots_with_no_batch_dims_saveable costs 23% here and
        # batch 2 fits without it)
        try:
            cfg16k = LlamaConfig(
                vocab_size=32000, hidden_size=768, intermediate_size=2048,
                num_hidden_layers=12, num_attention_heads=12,
                num_key_value_heads=12, max_position_embeddings=16384,
                use_flash_attention=True, dtype="bfloat16")
            detail["seq16384"] = _run_config(
                paddle, cfg16k, batch=2, seq=16384, steps=10, warmup=2)
        except Exception as e:  # noqa: BLE001
            detail["seq16384_error"] = f"{type(e).__name__}: {e}"[:200]

        # vision point: ResNet-50 train step (BASELINE's second metric)
        try:
            detail["resnet50"] = _run_resnet50(paddle)
        except Exception as e:  # noqa: BLE001
            detail["resnet50_error"] = f"{type(e).__name__}: {e}"[:200]

        # serving point: KV-cache decode throughput on the primary model
        try:
            detail["decode"] = _run_decode(paddle, cfg)
        except Exception as e:  # noqa: BLE001
            detail["decode_error"] = f"{type(e).__name__}: {e}"[:200]

        # weight-only int8 serving point (nn.quant): same decode, half
        # the weight bytes. At 134M params / batch 16 the decode is NOT
        # weight-bound, so int8 runs at parity here — the honest win is
        # the serving_big point below.
        try:
            detail["decode_int8"] = _run_decode(paddle, cfg,
                                                weight_only_int8=True)
        except Exception as e:  # noqa: BLE001
            detail["decode_int8_error"] = f"{type(e).__name__}: {e}"[:200]

        # bandwidth-bound serving: 1.34B params at batch 4 — decode time
        # is dominated by the weight read, so weight-only int8 should
        # (and does) win; this is where the reference's weight_only_linear
        # serving path earns its keep (quantized_linear.py:183)
        try:
            big_cfg = LlamaConfig(
                vocab_size=32000, hidden_size=2048, intermediate_size=5504,
                num_hidden_layers=24, num_attention_heads=16,
                num_key_value_heads=16, max_position_embeddings=2048,
                use_flash_attention=True, dtype="bfloat16")
            sb = _run_decode(paddle, big_cfg, batch=4)
            sb_i8 = _run_decode(paddle, big_cfg, batch=4,
                                weight_only_int8=True)
            n_params = (2 * 32000 * 2048
                        + 24 * (4 * 2048**2 + 3 * 2048 * 5504 + 2 * 2048)
                        + 2048) / 1e6
            detail["serving_big"] = {
                "params_m": round(n_params, 1), "bf16": sb, "int8": sb_i8,
                "int8_speedup": round(
                    sb_i8["decode_tokens_per_sec"]
                    / sb["decode_tokens_per_sec"], 3),
            }
        except Exception as e:  # noqa: BLE001
            detail["serving_big_error"] = f"{type(e).__name__}: {e}"[:200]

        # MoE point: 8-expert GShard decoder (routing + batched experts)
        try:
            detail["moe"] = _run_moe(paddle)
        except Exception as e:  # noqa: BLE001
            detail["moe_error"] = f"{type(e).__name__}: {e}"[:200]

        # (the old seq16384 fwd+bwd capability assert is superseded by
        # the measured detail["seq16384"] train-step point above)

    try:
        detail["telemetry"] = _telemetry_summary()
    except Exception as e:  # noqa: BLE001 — the bench must still print
        detail["telemetry_error"] = f"{type(e).__name__}: {e}"[:200]

    # the perf-regression gate's train lane reads this artifact
    # (benchmarks/perf_baseline.json train.* entries; run_shards.py
    # compares and fails loudly) — tok/s + MFU survive as a committed
    # file instead of only in the driver's BENCH_* trajectory
    try:
        import datetime

        train_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "benchmarks",
            "bench_train.json")
        with open(train_path, "w") as fh:
            json.dump({
                "bench": "llama_pretrain",
                "platform": backend,
                "finished": datetime.datetime.now(
                    datetime.timezone.utc).isoformat(timespec="seconds"),
                "tokens_per_sec_per_chip":
                    primary["tokens_per_sec_per_chip"],
                "mfu": primary.get("mfu"),
            }, fh, indent=1)
    except Exception:  # noqa: BLE001 — artifact write must not fail the bench
        pass

    print(json.dumps({
        "metric": "llama_pretrain_tokens_per_sec_per_chip",
        "value": primary["tokens_per_sec_per_chip"],
        "unit": "tokens/s/chip",
        "vs_baseline": (round(primary["tokens_per_sec_per_chip"] / 106650.5, 4)
                        if on_tpu else None),
        "detail": detail,
    }))


if __name__ == "__main__":
    main()
