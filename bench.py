"""Benchmark entry point: Llama pretrain step throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Metric: tokens/sec/chip on a Llama decoder pretrain step (the BASELINE.json
north-star metric family), measured with warmup-skip semantics matching the
reference's profiler ips counter (python/paddle/profiler/timer.py).

Model size is auto-scaled to the available accelerator: a ~110M-param
Llama on a single v5e chip (bf16, flash-attention on TPU), full 7B shapes
when a pod is attached.
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np


def main():
    import paddle_tpu as paddle
    from paddle_tpu.distributed.engine import ShardedTrainStep
    from paddle_tpu.distributed.mesh import ProcessMesh
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM, llama_pretrain_loss

    backend = jax.default_backend()
    on_tpu = backend not in ("cpu",)

    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=768, intermediate_size=2048,
            num_hidden_layers=12, num_attention_heads=12, num_key_value_heads=12,
            max_position_embeddings=2048, use_flash_attention=True, dtype="bfloat16")
        batch, seq, steps, warmup = 16, 1024, 20, 3
    else:  # CI smoke path
        cfg = LlamaConfig.tiny()
        batch, seq, steps, warmup = 4, 64, 5, 2

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
        # rope tables stay fp32 for precision
        model.llama.rope_cos._data = model.llama.rope_cos._data.astype(np.float32)
        model.llama.rope_sin._data = model.llama.rope_sin._data.astype(np.float32)

    n_dev = len(jax.devices())
    mesh = ProcessMesh(np.arange(n_dev), ["dp"])
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())
    step = ShardedTrainStep(model, llama_pretrain_loss, opt, mesh,
                            dp_axis="dp" if n_dev > 1 else None)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))

    # warmup (compile)
    for _ in range(warmup):
        loss = step.step(ids, labels)
    _ = float(loss)  # sync

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step.step(ids, labels)
    _ = float(loss)  # sync
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    per_chip = tokens_per_sec / max(n_dev, 1)

    print(json.dumps({
        "metric": "llama_pretrain_tokens_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": None,
        "detail": {
            "backend": backend, "n_devices": n_dev, "batch": batch, "seq": seq,
            "hidden": cfg.hidden_size, "layers": cfg.num_hidden_layers,
            "params_m": round(sum(int(np.prod(p.shape)) for p in model.parameters()) / 1e6, 1),
            "final_loss": round(float(loss), 4),
        },
    }))


if __name__ == "__main__":
    main()
